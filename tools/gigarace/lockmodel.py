"""The whole-library lock model: declarations, held-sets, order graph.

One pass over gigalint's per-file facts produces, for the whole
project:

- every lock the library creates (``threading.Lock/RLock/Condition``
  or the locktrace factories ``make_lock/make_rlock/make_condition``),
  with a canonical name (``pkg.mod.Cls._lock`` / ``pkg.mod._GLOBAL``)
  that matches the literal passed to the locktrace factory, so the
  static graph and the runtime sanitizer speak the same identities;
- per-function acquisition facts from a held-set walk of each body
  (``with lock:``, ``lock.acquire()``/``release()``, try-acquire and
  timeout forms), plus every call made and every ``self.X`` field
  touched while locks are held;
- the inter-lock order graph: an edge A -> B for every site that
  acquires B (directly or through a resolved callee) while holding A;
- per-class guarded-field classification for the race rule.

Resolution is conservative in gigalint's style — an unresolvable lock
expression or callee is ignored, never guessed — with three explicit
ways to teach the model what the AST alone cannot show:

- ``self.x = runlog  # gigarace: type RunLog`` pins an attribute's
  class when it arrives as an untyped parameter;
- ``self.f = {}  # gigarace: guarded-by _lock`` declares a field's
  guard; ``# gigarace: unguarded -- reason`` exempts a field whose
  cross-thread discipline is ownership transfer, not locking;
- constructor args that land in a lock-typed ``__init__`` parameter
  alias the callee's lock attribute to the caller's lock (the metrics
  instruments all share the registry lock this way).
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Dict, Iterable, Iterator, List, Optional, Set, Tuple

from tools.gigalint.astutils import dotted_name
from tools.gigalint.graph import Project
from tools.gigalint.walker import FunctionInfo, ModuleInfo

# attribute methods that mutate the container in place: a
# ``self._pending[k] = v`` / ``self._buf.append(x)`` is a WRITE to the
# field for guarded-field classification even though the attribute
# itself is only loaded
_MUTATOR_METHODS = frozenset({
    "append", "appendleft", "add", "clear", "discard", "extend",
    "insert", "pop", "popitem", "popleft", "remove", "setdefault",
    "update",
})

_TYPE_HINT_RE = re.compile(r"#\s*gigarace:\s*type\s+(?P<names>[\w.,\s]+)")
_CALLS_RE = re.compile(r"#\s*gigarace:\s*calls\s+(?P<names>[\w.,\s]+)")
_GUARDED_BY_RE = re.compile(r"#\s*gigarace:\s*guarded-by\s+(?P<attr>\w+)")
_UNGUARDED_RE = re.compile(r"#\s*gigarace:\s*unguarded\s*--\s*\S")

# methods named *_locked run with the caller already holding the
# class's lock (the flight-recorder discipline); *_from_signal methods
# are the sanctioned signal surface and do their own try-acquire
_CALLER_HOLDS_SUFFIX = "_locked"


@dataclasses.dataclass(frozen=True)
class LockDecl:
    name: str           # canonical: "pkg.mod.Cls._lock" / "pkg.mod._GLOBAL"
    kind: str           # "lock" | "rlock" | "condition"
    modname: str
    path: str
    lineno: int
    class_name: Optional[str]
    attr: str


@dataclasses.dataclass
class AcqSite:
    lock: LockDecl
    path: str
    lineno: int
    fn: FunctionInfo
    blocking: bool                 # False for timeout= / blocking=False
    held_before: Tuple[LockDecl, ...]


@dataclasses.dataclass
class BlockOp:
    kind: str      # "thread_join" | "cond_wait" | "socket_recv" | "sleep"
    detail: str
    path: str
    lineno: int
    held: Tuple[LockDecl, ...]     # locks held at the op (may be empty)


@dataclasses.dataclass
class HeldCall:
    callee: str
    path: str
    lineno: int
    held: Tuple[LockDecl, ...]


@dataclasses.dataclass
class FieldTouch:
    attr: str
    path: str
    lineno: int
    fn: FunctionInfo
    is_write: bool
    held: Tuple[LockDecl, ...]


@dataclasses.dataclass
class SignalReg:
    target: str    # dotted handler expression as written
    path: str
    lineno: int
    fn: Optional[FunctionInfo]     # enclosing function of the register call


@dataclasses.dataclass
class FnFacts:
    acquisitions: List[AcqSite] = dataclasses.field(default_factory=list)
    block_ops: List[BlockOp] = dataclasses.field(default_factory=list)
    held_calls: List[HeldCall] = dataclasses.field(default_factory=list)
    touches: List[FieldTouch] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class Edge:
    src: str
    dst: str
    path: str
    lineno: int
    note: str


class LockModel:
    def __init__(self, project: Project):
        self.project = project
        self.locks: Dict[str, LockDecl] = {}
        # (modname, Class) -> {attr: LockDecl}; module locks keyed class=None
        self.class_locks: Dict[Tuple[str, Optional[str]], Dict[str, LockDecl]] = {}
        # (modname, Class, attr) -> {(modname2, Class2), ...} candidates
        self.attr_types: Dict[Tuple[str, str, str], Set[Tuple[str, str]]] = {}
        # attrs assigned threading.Thread(...): (modname, Class) -> {attr}
        self.thread_attrs: Dict[Tuple[str, str], Set[str]] = {}
        # (modname, Class) -> {__init__ param name: attr it lands in}
        self.lock_params: Dict[Tuple[str, str], Dict[str, str]] = {}
        # declared field guards: (modname, Class, field) -> guard attr name
        self.guarded_decls: Dict[Tuple[str, str, str], str] = {}
        self.unguarded_decls: Set[Tuple[str, str, str]] = set()
        self.fn_facts: Dict[FunctionInfo, FnFacts] = {}
        # declared dynamic-dispatch targets: ``# gigarace: calls X.y``
        # on a call line teaches the model what an indirect call (an
        # observer list, a stored callback) may invoke
        self.calls_hints: Dict[FunctionInfo, Set[str]] = {}
        self.signal_regs: List[SignalReg] = []
        self.edges: Dict[Tuple[str, str], List[Edge]] = {}
        self._may_acquire: Dict[FunctionInfo, Set[str]] = {}
        self._may_block: Dict[FunctionInfo, Dict[str, str]] = {}
        self._callees: Dict[FunctionInfo, List[FunctionInfo]] = {}
        self._build()

    # ------------------------------------------------------------------
    # construction
    # ------------------------------------------------------------------
    def _build(self) -> None:
        mods = list(self.project.modules.values())
        for mod in mods:
            self._collect_decls(mod)
        for mod in mods:
            self._collect_aliases(mod)
        self._declare_unaliased_params()
        for mod in mods:
            for fn in mod.functions.values():
                self.fn_facts[fn] = _FnWalker(self, fn).run()
        self._resolve_callees()
        self._propagate()
        self._build_edges()

    # -- pass A: lock declarations, attr types, annotations ----------------
    def _lock_ctor(self, call: ast.Call, mod: ModuleInfo) -> Optional[Tuple[str, Optional[str]]]:
        """(kind, literal name) when ``call`` constructs a lock."""
        fname = dotted_name(call.func)
        if not fname:
            return None
        last = fname.rsplit(".", 1)[-1]
        factory = {"make_lock": "lock", "make_rlock": "rlock",
                   "make_condition": "condition"}.get(last)
        if factory:
            lit = None
            if call.args and isinstance(call.args[0], ast.Constant) \
                    and isinstance(call.args[0].value, str):
                lit = call.args[0].value
            return factory, lit
        kind = {"Lock": "lock", "RLock": "rlock",
                "Condition": "condition"}.get(last)
        if kind is None:
            return None
        # require a threading provenance: ``threading.Lock()`` or a
        # ``from threading import Lock`` alias — not any class named Lock
        if fname == f"threading.{last}":
            return kind, None
        target = mod.imports.get(fname)
        if target == f"threading.{last}":
            return kind, None
        head = fname.split(".")[0]
        if mod.imports.get(head) == "threading":
            return kind, None
        return None

    def _line_comment(self, mod: ModuleInfo, lineno: int) -> str:
        if 1 <= lineno <= len(mod.source_lines):
            return mod.source_lines[lineno - 1]
        return ""

    def _declare(self, mod: ModuleInfo, class_name: Optional[str],
                 attr: str, kind: str, literal: Optional[str],
                 lineno: int) -> None:
        derived = (f"{mod.modname}.{class_name}.{attr}" if class_name
                   else f"{mod.modname}.{attr}")
        name = literal or derived
        decl = LockDecl(name=name, kind=kind, modname=mod.modname,
                        path=mod.path, lineno=lineno,
                        class_name=class_name, attr=attr)
        # first declaration wins (re-assignment in reset paths is the
        # same lock identity)
        self.locks.setdefault(name, decl)
        self.class_locks.setdefault((mod.modname, class_name), {}) \
            .setdefault(attr, self.locks[name])

    def _hint_classes(self, mod: ModuleInfo, names: Iterable[str]) -> Set[Tuple[str, str]]:
        out: Set[Tuple[str, str]] = set()
        for raw in names:
            cname = raw.strip()
            if not cname:
                continue
            hit = self._find_class(mod, cname)
            if hit:
                out.add(hit)
        return out

    def _find_class(self, mod: ModuleInfo, cname: str) -> Optional[Tuple[str, str]]:
        """Resolve a class name (possibly dotted / imported) to
        (modname, Class) of a scanned class."""
        target = mod.imports.get(cname, None)
        candidates = []
        if target:
            candidates.append(target)
        candidates.append(f"{mod.modname}.{cname}" if "." not in cname else cname)
        for dotted in candidates:
            pkg, _, cls = dotted.rpartition(".")
            m2 = self.project.modules.get(pkg)
            if m2 and any(q == cls or q.startswith(cls + ".")
                          for q in m2.functions):
                return (pkg, cls)
        # same-module class with methods
        if any(q.startswith(cname + ".") for q in mod.functions):
            return (mod.modname, cname)
        return None

    def _value_classes(self, mod: ModuleInfo, fn: Optional[FunctionInfo],
                       value: ast.AST, depth: int = 0) -> Set[Tuple[str, str]]:
        """Classes an assignment's value may be an instance of:
        constructor calls anywhere in the expression, plus one level of
        factory-return inference."""
        out: Set[Tuple[str, str]] = set()
        for node in _shallow_walk(value, include_root=True):
            if not isinstance(node, ast.Call):
                continue
            fname = dotted_name(node.func)
            if not fname:
                continue
            hit = self._find_class(mod, fname)
            if hit:
                out.add(hit)
                continue
            if depth == 0:
                factory = self.project.resolve(mod, fn, fname)
                if factory is not None:
                    for sub in _shallow_walk(factory.node):
                        if isinstance(sub, ast.Call):
                            out |= self._value_classes(
                                factory.module, factory, sub, depth=1)
        return out

    def _collect_decls(self, mod: ModuleInfo) -> None:
        # module-level locks
        for stmt in mod.tree.body:
            if isinstance(stmt, ast.Assign) and isinstance(stmt.value, ast.Call):
                ctor = self._lock_ctor(stmt.value, mod)
                if ctor:
                    for tgt in stmt.targets:
                        if isinstance(tgt, ast.Name):
                            self._declare(mod, None, tgt.id, ctor[0],
                                          ctor[1], stmt.lineno)
        # instance locks / attr types / field annotations, in any method
        for fn in mod.functions.values():
            if not fn.class_name:
                continue
            cls = fn.class_name
            for stmt in _shallow_walk(fn.node):
                value = None
                targets: List[ast.AST] = []
                if isinstance(stmt, ast.Assign):
                    value, targets = stmt.value, stmt.targets
                elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                    value, targets = stmt.value, [stmt.target]
                if value is None:
                    continue
                self_attrs = [t.attr for t in targets
                              if isinstance(t, ast.Attribute)
                              and isinstance(t.value, ast.Name)
                              and t.value.id == "self"]
                if not self_attrs:
                    continue
                line = self._line_comment(mod, stmt.lineno)
                m = _GUARDED_BY_RE.search(line)
                if m:
                    for attr in self_attrs:
                        self.guarded_decls[(mod.modname, cls, attr)] = \
                            m.group("attr")
                if _UNGUARDED_RE.search(line):
                    for attr in self_attrs:
                        self.unguarded_decls.add((mod.modname, cls, attr))
                if isinstance(value, ast.Call):
                    ctor = self._lock_ctor(value, mod)
                    if ctor:
                        for attr in self_attrs:
                            self._declare(mod, cls, attr, ctor[0],
                                          ctor[1], stmt.lineno)
                        continue
                    fname = dotted_name(value.func)
                    if fname and fname in ("threading.Thread", "Thread") and (
                            fname == "threading.Thread"
                            or mod.imports.get("Thread") == "threading.Thread"):
                        for attr in self_attrs:
                            self.thread_attrs.setdefault(
                                (mod.modname, cls), set()).add(attr)
                # annotated __init__ param landing in an attribute:
                # a lock type feeds the alias pass, any scanned class
                # feeds attr_types (``flight: Optional[FlightRecorder]``
                # needs no comment hint)
                if fn.name == "__init__" and isinstance(value, ast.Name) \
                        and value.id in fn.params:
                    ann = _param_annotation(fn.node, value.id)
                    if ann and ann.rsplit(".", 1)[-1] in (
                            "Lock", "RLock", "Condition"):
                        for attr in self_attrs:
                            self.lock_params.setdefault(
                                (mod.modname, cls), {})[value.id] = attr
                    elif ann:
                        hit = self._find_class(mod, ann)
                        if hit:
                            for attr in self_attrs:
                                self.attr_types.setdefault(
                                    (mod.modname, cls, attr), set()).add(hit)
                # attribute class: type hint comment, annotation, ctors
                hint = _TYPE_HINT_RE.search(line)
                classes: Set[Tuple[str, str]] = set()
                if hint:
                    classes |= self._hint_classes(
                        mod, hint.group("names").split(","))
                if isinstance(stmt, ast.AnnAssign):
                    ann_name = _annotation_name(stmt.annotation)
                    if ann_name:
                        classes |= self._hint_classes(mod, [ann_name])
                classes |= self._value_classes(mod, fn, value)
                if classes:
                    for attr in self_attrs:
                        self.attr_types.setdefault(
                            (mod.modname, cls, attr), set()).update(classes)

    # -- pass B: alias the lock-typed ctor params to the caller's lock -----
    def _collect_aliases(self, mod: ModuleInfo) -> None:
        for fn in mod.functions.values():
            for node in _shallow_walk(fn.node):
                if not isinstance(node, ast.Call):
                    continue
                fname = dotted_name(node.func)
                if not fname:
                    continue
                hit = self._find_class(mod, fname)
                if hit is None or hit not in self.lock_params:
                    continue
                init = self.project.modules[hit[0]].functions.get(
                    f"{hit[1]}.__init__")
                if init is None:
                    continue
                params = [p for p in init.params if p != "self"]
                for pname, attr in self.lock_params[hit].items():
                    arg = _call_arg(node, params, pname)
                    if arg is None:
                        continue
                    src = dotted_name(arg)
                    decl = self._resolve_lock_text(src, mod, fn) if src else None
                    if decl is not None:
                        self.class_locks.setdefault(hit, {})[attr] = decl

    def _declare_unaliased_params(self) -> None:
        # lock-param attrs nobody aliased still need an identity so their
        # acquisitions resolve (standalone construction in tests/tools).
        # Runs AFTER every module's alias pass: doing this per-module
        # would mint a phantom standalone lock for a class whose aliasing
        # call site simply lives in a later module.
        for key, params in self.lock_params.items():
            for attr in params.values():
                if attr not in self.class_locks.get(key, {}):
                    m2 = self.project.modules.get(key[0])
                    if m2 is not None:
                        self._declare(m2, key[1], attr, "lock", None, 1)

    # -- lock expression resolution ----------------------------------------
    def _resolve_lock_text(self, text: Optional[str], mod: ModuleInfo,
                           fn: Optional[FunctionInfo]) -> Optional[LockDecl]:
        if not text:
            return None
        parts = text.split(".")
        if parts[0] == "self" and fn is not None and fn.class_name:
            if len(parts) == 2:
                return self.class_locks.get(
                    (mod.modname, fn.class_name), {}).get(parts[1])
            if len(parts) == 3:
                for owner in self.attr_types.get(
                        (mod.modname, fn.class_name, parts[1]), ()):
                    hit = self.class_locks.get(owner, {}).get(parts[2])
                    if hit:
                        return hit
            return None
        if len(parts) == 1:
            return self.class_locks.get((mod.modname, None), {}).get(parts[0])
        return None

    # -- callee resolution (gigalint resolve + attr types) ------------------
    def resolve_callees(self, fn: FunctionInfo, callee: str) -> List[FunctionInfo]:
        if callee in self.calls_hints.get(fn, ()):
            return self._resolve_hint_target(fn.module, callee)
        hit = self.project.resolve(fn.module, fn, callee)
        if hit is not None:
            return [hit]
        parts = callee.split(".")
        if parts[0] == "self" and fn.class_name and len(parts) == 3:
            out = []
            for (m2, c2) in sorted(self.attr_types.get(
                    (fn.module.modname, fn.class_name, parts[1]), ())):
                mod2 = self.project.modules.get(m2)
                f2 = mod2.functions.get(f"{c2}.{parts[2]}") if mod2 else None
                if f2 is not None:
                    out.append(f2)
            return out
        return []

    def _resolve_hint_target(self, mod: ModuleInfo, name: str) -> List[FunctionInfo]:
        """Resolve a ``# gigarace: calls`` target: dotted class paths,
        imported names, and bare ``Cls.meth`` qualnames anywhere in the
        scanned tree (hint targets commonly live in modules the hinted
        module deliberately does NOT import — that indirection is why
        the call is dynamic in the first place)."""
        if "." in name:
            head, meth = name.rsplit(".", 1)
            hit = self._find_class(mod, head)
            if hit is not None:
                m2 = self.project.modules.get(hit[0])
                f2 = m2.functions.get(f"{hit[1]}.{meth}") if m2 else None
                return [f2] if f2 is not None else []
        out = []
        for modname in sorted(self.project.modules):
            f2 = self.project.modules[modname].functions.get(name)
            if f2 is not None:
                out.append(f2)
        return out

    def _resolve_callees_cached(self, fn: FunctionInfo) -> List[FunctionInfo]:
        hit = self._callees.get(fn)
        if hit is None:
            hit = []
            seen = set()
            for site in fn.calls:
                for callee in self.resolve_callees(fn, site.callee):
                    if callee is not fn and id(callee) not in seen:
                        seen.add(id(callee))
                        hit.append(callee)
            for name in sorted(self.calls_hints.get(fn, ())):
                for callee in self._resolve_hint_target(fn.module, name):
                    if callee is not fn and id(callee) not in seen:
                        seen.add(id(callee))
                        hit.append(callee)
            self._callees[fn] = hit
        return hit

    def _resolve_callees(self) -> None:
        for fn in self.fn_facts:
            self._resolve_callees_cached(fn)

    # -- transitive may-acquire / may-block ---------------------------------
    def _propagate(self) -> None:
        for fn, facts in self.fn_facts.items():
            self._may_acquire[fn] = {a.lock.name for a in facts.acquisitions}
            blocks: Dict[str, str] = {}
            for op in facts.block_ops:
                blocks.setdefault(op.kind,
                                  f"{op.detail} at {op.path}:{op.lineno}")
            self._may_block[fn] = blocks
        changed = True
        while changed:
            changed = False
            for fn in self.fn_facts:
                for callee in self._callees.get(fn, ()):
                    extra = self._may_acquire.get(callee, set()) \
                        - self._may_acquire[fn]
                    if extra:
                        self._may_acquire[fn] |= extra
                        changed = True
                    for kind, why in self._may_block.get(callee, {}).items():
                        if kind not in self._may_block[fn]:
                            self._may_block[fn][kind] = \
                                f"via {callee.qualname}: {why}"
                            changed = True

    def may_acquire(self, fn: FunctionInfo) -> Set[str]:
        return self._may_acquire.get(fn, set())

    def may_block(self, fn: FunctionInfo) -> Dict[str, str]:
        return self._may_block.get(fn, {})

    # -- the order graph -----------------------------------------------------
    def _add_edge(self, src: LockDecl, dst_name: str, path: str,
                  lineno: int, note: str) -> None:
        if src.name == dst_name:
            return  # self-acquisition is GL018's self-deadlock check
        self.edges.setdefault((src.name, dst_name), []).append(
            Edge(src.name, dst_name, path, lineno, note))

    def _build_edges(self) -> None:
        for fn, facts in self.fn_facts.items():
            for acq in facts.acquisitions:
                for h in acq.held_before:
                    self._add_edge(h, acq.lock.name, acq.path, acq.lineno,
                                   f"acquired in {fn.qualname}")
            for call in facts.held_calls:
                for callee in self.resolve_callees(fn, call.callee):
                    for lname in self._may_acquire.get(callee, ()):
                        for h in call.held:
                            self._add_edge(
                                h, lname, call.path, call.lineno,
                                f"{fn.qualname} calls {callee.qualname}")

    # -- cycle detection -------------------------------------------------
    def cycles(self) -> List[List[str]]:
        """Strongly-connected components of size > 1, sorted."""
        adj: Dict[str, Set[str]] = {}
        for (a, b) in self.edges:
            adj.setdefault(a, set()).add(b)
            adj.setdefault(b, set())
        index: Dict[str, int] = {}
        low: Dict[str, int] = {}
        on_stack: Set[str] = set()
        stack: List[str] = []
        out: List[List[str]] = []
        counter = [0]

        def strongconnect(v: str) -> None:
            # iterative Tarjan: fixture cycles are tiny but recursion
            # depth must not depend on graph shape
            work = [(v, iter(sorted(adj[v])))]
            index[v] = low[v] = counter[0]
            counter[0] += 1
            stack.append(v)
            on_stack.add(v)
            while work:
                node, it = work[-1]
                advanced = False
                for w in it:
                    if w not in index:
                        index[w] = low[w] = counter[0]
                        counter[0] += 1
                        stack.append(w)
                        on_stack.add(w)
                        work.append((w, iter(sorted(adj[w]))))
                        advanced = True
                        break
                    if w in on_stack:
                        low[node] = min(low[node], index[w])
                if advanced:
                    continue
                work.pop()
                if work:
                    parent = work[-1][0]
                    low[parent] = min(low[parent], low[node])
                if low[node] == index[node]:
                    scc = []
                    while True:
                        w = stack.pop()
                        on_stack.discard(w)
                        scc.append(w)
                        if w == node:
                            break
                    if len(scc) > 1:
                        out.append(sorted(scc))

        for v in sorted(adj):
            if v not in index:
                strongconnect(v)
        return sorted(out)

    def self_deadlocks(self) -> List[AcqSite]:
        """Re-acquisition of a non-reentrant lock already held."""
        out = []
        for facts in self.fn_facts.values():
            for acq in facts.acquisitions:
                if acq.lock.kind != "rlock" and any(
                        h.name == acq.lock.name for h in acq.held_before):
                    out.append(acq)
        return out

    # -- signal roots -------------------------------------------------------
    def signal_roots(self) -> Dict[FunctionInfo, str]:
        roots: Dict[FunctionInfo, str] = {}
        for reg in self.signal_regs:
            mod = (reg.fn.module if reg.fn is not None
                   else self.project.modules.get(
                       _modname_of_path(self.project, reg.path)))
            if mod is None:
                continue
            hit = self.project.resolve(mod, reg.fn, reg.target)
            if hit is not None:
                roots.setdefault(
                    hit, f"registered as signal handler at "
                         f"{reg.path}:{reg.lineno}")
        return roots

    def signal_reachable(self) -> Dict[FunctionInfo, str]:
        roots = self.signal_roots()
        reached = dict(roots)
        queue = list(roots.items())
        while queue:
            fn, why = queue.pop()
            for callee in self._resolve_callees_cached(fn):
                if callee in reached:
                    continue
                via = f"called from {fn.qualname} ({why})"
                reached[callee] = via
                queue.append((callee, via))
        return reached


def _modname_of_path(project: Project, path: str) -> Optional[str]:
    for name, mod in project.modules.items():
        if mod.path == path:
            return name
    return None


def _param_annotation(fn_node: ast.AST, pname: str) -> Optional[str]:
    a = fn_node.args
    for p in list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs):
        if p.arg == pname and p.annotation is not None:
            return _annotation_name(p.annotation)
    return None


def _annotation_name(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value.strip().strip('"\'')
    name = dotted_name(node)
    if name:
        return name
    # Optional[X] / "Optional[X]"-style subscripts: take the inner name
    if isinstance(node, ast.Subscript):
        return _annotation_name(node.slice)
    return None


def _call_arg(call: ast.Call, params: List[str], pname: str) -> Optional[ast.AST]:
    for kw in call.keywords:
        if kw.arg == pname:
            return kw.value
    try:
        idx = params.index(pname)
    except ValueError:
        return None
    if idx < len(call.args) and not any(
            isinstance(a, ast.Starred) for a in call.args[: idx + 1]):
        return call.args[idx]
    return None


def _shallow_walk(node: ast.AST, include_root: bool = False) -> Iterator[ast.AST]:
    """ast.walk that does not descend into nested function/class scopes
    (their statements belong to their own FunctionInfo)."""
    queue: List[ast.AST] = [node]
    first = True
    while queue:
        n = queue.pop()
        if not first or include_root:
            yield n
        if first or not isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                                       ast.ClassDef, ast.Lambda)):
            queue.extend(ast.iter_child_nodes(n))
        first = False


def _is_blocking_acquire(call: ast.Call) -> bool:
    """``acquire()`` with no timeout and blocking != False is indefinite."""
    for kw in call.keywords:
        if kw.arg == "timeout":
            return False
        if kw.arg == "blocking" and isinstance(kw.value, ast.Constant) \
                and kw.value.value is False:
            return False
    if call.args:
        a0 = call.args[0]
        if isinstance(a0, ast.Constant) and a0.value is False:
            return False
        if len(call.args) >= 2:  # acquire(True, timeout)
            return False
    return True


class _FnWalker:
    """Held-set walk of one function body, in statement order."""

    def __init__(self, model: LockModel, fn: FunctionInfo):
        self.model = model
        self.fn = fn
        self.mod = fn.module
        self.held: List[LockDecl] = []
        self.facts = FnFacts()
        self.local_threads: Set[str] = set()
        self._socket_mod = any(
            t == "socket" or t.startswith("socket.")
            for t in self.mod.imports.values())

    def run(self) -> FnFacts:
        # *_locked methods run with the caller already holding every
        # lock of their class — seed the held set accordingly
        if self.fn.name.endswith(_CALLER_HOLDS_SUFFIX) and self.fn.class_name:
            self.held.extend(sorted(
                self.model.class_locks.get(
                    (self.mod.modname, self.fn.class_name), {}).values(),
                key=lambda d: d.name))
        self._walk(self.fn.node.body)
        return self.facts

    # -- helpers -----------------------------------------------------------
    def _snapshot(self) -> Tuple[LockDecl, ...]:
        return tuple(self.held)

    def _acquire(self, decl: LockDecl, lineno: int, blocking: bool) -> None:
        self.facts.acquisitions.append(AcqSite(
            lock=decl, path=self.mod.path, lineno=lineno, fn=self.fn,
            blocking=blocking, held_before=self._snapshot()))
        self.held.append(decl)

    def _release(self, decl: LockDecl) -> None:
        for i in range(len(self.held) - 1, -1, -1):
            if self.held[i].name == decl.name:
                del self.held[i]
                return

    def _resolve_lock(self, text: Optional[str]) -> Optional[LockDecl]:
        return self.model._resolve_lock_text(text, self.mod, self.fn)

    # -- statement walk ------------------------------------------------------
    def _walk(self, stmts: Iterable[ast.stmt]) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                pushed = []
                for item in stmt.items:
                    self._scan_expr(item.context_expr)
                    decl = self._resolve_lock(
                        dotted_name(item.context_expr))
                    if decl is not None:
                        self._acquire(decl, item.context_expr.lineno,
                                      blocking=True)
                        pushed.append(decl)
                self._walk(stmt.body)
                for decl in reversed(pushed):
                    self._release(decl)
                continue
            if isinstance(stmt, ast.Try):
                self._walk(stmt.body)
                for handler in stmt.handlers:
                    self._walk(handler.body)
                self._walk(stmt.orelse)
                self._walk(stmt.finalbody)
                continue
            if isinstance(stmt, ast.If):
                self._scan_expr(stmt.test)
                saved = list(self.held)
                self._walk(stmt.body)
                self.held = list(saved)
                self._walk(stmt.orelse)
                self.held = saved
                continue
            if isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
                if isinstance(stmt, ast.While):
                    self._scan_expr(stmt.test)
                else:
                    self._scan_expr(stmt.iter)
                self._walk(stmt.body)
                self._walk(stmt.orelse)
                continue
            # plain statement: scan its expressions in order
            for node in ast.iter_child_nodes(stmt):
                self._scan_expr(node)
            self._track_locals(stmt)

    def _track_locals(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, ast.Assign) and isinstance(stmt.value, ast.Call):
            fname = dotted_name(stmt.value.func)
            if fname and fname.rsplit(".", 1)[-1] == "Thread":
                for tgt in stmt.targets:
                    if isinstance(tgt, ast.Name):
                        self.local_threads.add(tgt.id)

    def _is_thread(self, base: str) -> bool:
        if base in self.local_threads:
            return True
        parts = base.split(".")
        if parts[0] == "self" and len(parts) == 2 and self.fn.class_name:
            return parts[1] in self.model.thread_attrs.get(
                (self.mod.modname, self.fn.class_name), set())
        return False

    # -- expression scan -----------------------------------------------------
    def _scan_expr(self, node: ast.AST) -> None:
        for sub in _walk_expr(node):
            if isinstance(sub, ast.Call):
                self._handle_call(sub)
            elif isinstance(sub, ast.Attribute):
                self._handle_attribute(sub)
            elif isinstance(sub, ast.Subscript):
                self._handle_subscript(sub)

    def _handle_call(self, node: ast.Call) -> None:
        hint = _CALLS_RE.search(self.model._line_comment(self.mod, node.lineno))
        if hint:
            names = {n.strip() for n in hint.group("names").split(",")
                     if n.strip()}
            self.model.calls_hints.setdefault(self.fn, set()).update(names)
            if self.held:
                # an indirect call under a lock contributes order edges
                # exactly like a resolved one
                for name in sorted(names):
                    self.facts.held_calls.append(HeldCall(
                        callee=name, path=self.mod.path,
                        lineno=node.lineno, held=self._snapshot()))
        fname = dotted_name(node.func)
        if not fname:
            return
        parts = fname.rsplit(".", 1)
        base = parts[0] if len(parts) == 2 else None
        last = parts[-1]
        if base is not None:
            if last == "acquire":
                decl = self._resolve_lock(base)
                if decl is not None:
                    self._acquire(decl, node.lineno,
                                  blocking=_is_blocking_acquire(node))
                    return
            elif last == "release":
                decl = self._resolve_lock(base)
                if decl is not None:
                    self._release(decl)
                    return
            elif last in ("wait", "wait_for"):
                decl = self._resolve_lock(base)
                if decl is not None and decl.kind == "condition":
                    others = tuple(h for h in self.held
                                   if h.name != decl.name)
                    self.facts.block_ops.append(BlockOp(
                        kind="cond_wait", detail=f"{base}.{last}()",
                        path=self.mod.path, lineno=node.lineno, held=others))
                    return
            elif last == "join" and self._is_thread(base):
                self.facts.block_ops.append(BlockOp(
                    kind="thread_join", detail=f"{base}.join()",
                    path=self.mod.path, lineno=node.lineno,
                    held=self._snapshot()))
            elif last in ("recv", "recv_into", "accept") and self._socket_mod:
                self.facts.block_ops.append(BlockOp(
                    kind="socket_recv", detail=f"{fname}()",
                    path=self.mod.path, lineno=node.lineno,
                    held=self._snapshot()))
        if fname == "time.sleep" or (
                fname == "sleep" and self.mod.imports.get("sleep") == "time.sleep"):
            self.facts.block_ops.append(BlockOp(
                kind="sleep", detail="time.sleep()",
                path=self.mod.path, lineno=node.lineno,
                held=self._snapshot()))
        if last == "register_signal_callback" and node.args:
            target = dotted_name(node.args[0])
            if target:
                self.model.signal_regs.append(SignalReg(
                    target=target, path=self.mod.path,
                    lineno=node.lineno, fn=self.fn))
        elif (fname == "signal.signal" or fname.endswith(".signal.signal")) \
                and len(node.args) >= 2:
            target = dotted_name(node.args[1])
            if target:
                self.model.signal_regs.append(SignalReg(
                    target=target, path=self.mod.path,
                    lineno=node.lineno, fn=self.fn))
        # ``self.X.append(...)`` mutates field X in place: a write for
        # guarded-field classification
        fparts = fname.split(".")
        if (len(fparts) == 3 and fparts[0] == "self"
                and fparts[2] in _MUTATOR_METHODS and self.fn.class_name):
            self.facts.touches.append(FieldTouch(
                attr=fparts[1], path=self.mod.path, lineno=node.lineno,
                fn=self.fn, is_write=True, held=self._snapshot()))
        if self.held:
            self.facts.held_calls.append(HeldCall(
                callee=fname, path=self.mod.path, lineno=node.lineno,
                held=self._snapshot()))

    def _handle_attribute(self, node: ast.Attribute) -> None:
        if not (isinstance(node.value, ast.Name) and node.value.id == "self"
                and self.fn.class_name):
            return
        key = (self.mod.modname, self.fn.class_name)
        if node.attr in self.model.class_locks.get(key, {}):
            return  # the lock itself is not a guarded field
        if f"{self.fn.class_name}.{node.attr}" in self.mod.functions:
            return  # a method reference, not field state
        is_write = isinstance(node.ctx, (ast.Store, ast.Del))
        self.facts.touches.append(FieldTouch(
            attr=node.attr, path=self.mod.path, lineno=node.lineno,
            fn=self.fn, is_write=is_write, held=self._snapshot()))

    def _handle_subscript(self, node: ast.Subscript) -> None:
        # self.X[k] = v / del self.X[k]: a write to field X
        if isinstance(node.ctx, (ast.Store, ast.Del)) \
                and isinstance(node.value, ast.Attribute) \
                and isinstance(node.value.value, ast.Name) \
                and node.value.value.id == "self" and self.fn.class_name:
            self.facts.touches.append(FieldTouch(
                attr=node.value.attr, path=self.mod.path,
                lineno=node.lineno, fn=self.fn, is_write=True,
                held=self._snapshot()))


def _walk_expr(node: ast.AST) -> Iterator[ast.AST]:
    """Walk an expression without entering Lambda bodies; mutator-method
    calls on ``self.X`` are rewritten as write touches by the caller via
    the Call handler, so plain walk order is fine here."""
    queue: List[ast.AST] = [node]
    while queue:
        n = queue.pop(0)
        yield n
        if isinstance(n, ast.Lambda):
            continue
        queue.extend(ast.iter_child_nodes(n))


def build_lock_model(project: Project) -> LockModel:
    return LockModel(project)
