"""GL018-GL021: the lock-discipline and signal-safety rules.

Registered into gigalint's rule registry, so ``scripts/lint.sh``'s one
``python -m tools.gigalint gigapath_tpu scripts tests`` invocation (and
every ``run_lint`` call in the tier-1 tests) runs them alongside
GL001-GL017 with the same waiver machinery:

- GL018 — a cycle in the inter-lock acquisition order (two threads
  entering the cycle from different nodes deadlock), including
  re-acquiring a non-reentrant lock already held on the same stack;
- GL019 — guarded-field discipline: a field written under lock L in
  one method and touched without L elsewhere in the same class is a
  data race (declare intent with ``# gigarace: guarded-by _lock`` /
  ``# gigarace: unguarded -- reason`` on the field's init line);
- GL020 — signal-handler reachability: code reachable from a
  ``register_signal_callback`` / ``signal.signal`` chain may not make
  an indefinite (non-try) lock acquisition or call buffered ``print``
  — the handler may have interrupted the very thread that holds the
  lock (generalizes GL011 from "where handlers live" to "what handlers
  may call");
- GL021 — blocking calls made while holding a lock: ``Thread.join``,
  ``Condition.wait`` on a different lock, blocking socket reads and
  ``time.sleep`` stall every other thread contending for the lock.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

from tools.gigalint.graph import Project
from tools.gigalint.rules import Finding, register
from tools.gigalint.walker import ModuleInfo
from tools.gigarace.lockmodel import LockDecl, LockModel, build_lock_model

RACE_RULES = ("GL018", "GL019", "GL020", "GL021")

_EXEMPT_SEGMENTS = frozenset({"scripts", "tests", "demo"})

_BLOCK_KIND_PROSE = {
    "thread_join": "Thread.join()",
    "cond_wait": "Condition.wait() on a different lock",
    "socket_recv": "a blocking socket read/accept",
    "sleep": "time.sleep()",
}


def _exempt(mod: ModuleInfo) -> bool:
    segments = mod.path.split("/")
    return mod.is_test_file or any(
        s in _EXEMPT_SEGMENTS for s in segments)


def model_for(project: Project) -> LockModel:
    """One LockModel per lint invocation, shared by all four rules;
    built over the non-exempt modules only, so test/driver threading
    never shapes the library's lock graph."""
    cached = getattr(project, "_gigarace_model", None)
    if cached is not None:
        return cached
    sub = Project(
        modules={name: mod for name, mod in project.modules.items()
                 if not _exempt(mod)},
        root=project.root,
    )
    model = build_lock_model(sub)
    project._gigarace_model = model
    return model


# ---------------------------------------------------------------------------
# GL018 — lock-order cycles
# ---------------------------------------------------------------------------

@register(
    "GL018",
    "cycle in the inter-lock acquisition order: two threads entering the "
    "cycle from different locks deadlock; establish one global order",
)
def check_lock_order(project: Project) -> List[Finding]:
    model = model_for(project)
    findings: List[Finding] = []
    for acq in sorted(model.self_deadlocks(),
                      key=lambda a: (a.path, a.lineno)):
        findings.append(Finding(
            rule="GL018", path=acq.path, lineno=acq.lineno,
            symbol=acq.fn.qualname,
            message=f"re-acquisition of non-reentrant lock "
            f"'{acq.lock.name}' already held on this stack: guaranteed "
            "self-deadlock. Split the locked region or use an RLock "
            "with documented re-entrancy.",
        ))
    for scc in model.cycles():
        in_cycle = set(scc)
        sites = []
        for (a, b), edges in sorted(model.edges.items()):
            if a in in_cycle and b in in_cycle:
                e = edges[0]
                sites.append(e)
        if not sites:
            continue
        anchor = min(sites, key=lambda e: (e.path, e.lineno))
        chain = " -> ".join(scc + [scc[0]])
        detail = "; ".join(
            f"{e.src} -> {e.dst} at {e.path}:{e.lineno} ({e.note})"
            for e in sites)
        findings.append(Finding(
            rule="GL018", path=anchor.path, lineno=anchor.lineno,
            symbol=scc[0],
            message=f"lock-order cycle {chain}: potential deadlock. "
            f"Edges: {detail}. Pick one global acquisition order and "
            "restructure the odd edge out (move the nested acquire "
            "outside the outer lock).",
        ))
    return findings


# ---------------------------------------------------------------------------
# GL019 — guarded-field discipline
# ---------------------------------------------------------------------------

def _own_lock_names(model: LockModel, key: Tuple[str, Optional[str]]) -> Dict[str, LockDecl]:
    return model.class_locks.get(key, {})


def resolved_field_guards(
    model: LockModel,
) -> Dict[Tuple[str, str, str], Tuple[LockDecl, list]]:
    """(modname, class, attr) -> (guard lock, touches) for every field
    with a resolvable guard.

    The resolution GL019 enforces and ``--inventory`` reports: an
    explicit ``# gigarace: guarded-by`` declaration wins; otherwise the
    class's own lock held during the most non-``__init__`` writes.
    Fields declared ``# gigarace: unguarded`` are excluded.
    """
    by_field: Dict[Tuple[str, str, str], list] = {}
    for fn, facts in model.fn_facts.items():
        if not fn.class_name:
            continue
        for t in facts.touches:
            by_field.setdefault(
                (fn.module.modname, fn.class_name, t.attr), []).append(t)
    out: Dict[Tuple[str, str, str], Tuple[LockDecl, list]] = {}
    for (modname, cls, attr), touches in by_field.items():
        if (modname, cls, attr) in model.unguarded_decls:
            continue
        own = _own_lock_names(model, (modname, cls))
        if not own:
            continue
        own_names = {d.name for d in own.values()}
        guard: Optional[LockDecl] = None
        declared = model.guarded_decls.get((modname, cls, attr))
        if declared is not None:
            guard = own.get(declared) or model.class_locks.get(
                (modname, None), {}).get(declared)
        else:
            counts: Dict[str, int] = {}
            for t in touches:
                if not t.is_write or t.fn.name == "__init__":
                    continue
                for h in t.held:
                    if h.name in own_names:
                        counts[h.name] = counts.get(h.name, 0) + 1
            if counts:
                best = max(sorted(counts), key=lambda n: counts[n])
                guard = model.locks.get(best)
        if guard is not None:
            out[(modname, cls, attr)] = (guard, touches)
    return out


@register(
    "GL019",
    "field written under a lock in one method but touched without it in "
    "another: a data race; hold the guard at every touch or declare "
    "'# gigarace: unguarded -- reason' for single-owner handoffs",
)
def check_guarded_fields(project: Project) -> List[Finding]:
    model = model_for(project)
    findings: List[Finding] = []
    for (modname, cls, attr), (guard, touches) in sorted(
            resolved_field_guards(model).items()):
        for t in sorted(touches, key=lambda t: (t.path, t.lineno)):
            if t.fn.name == "__init__":
                continue  # construction happens-before publication
            if guard.name in {h.name for h in t.held}:
                continue
            kind = "written" if t.is_write else "read"
            findings.append(Finding(
                rule="GL019", path=t.path, lineno=t.lineno,
                symbol=t.fn.qualname,
                message=f"field '{attr}' of {cls} is guarded by "
                f"'{guard.name}' (written under it elsewhere) but {kind} "
                "here without holding it: data race. Acquire the guard, "
                "or declare the field '# gigarace: unguarded -- reason' "
                "at its __init__ assignment if ownership transfer makes "
                "this safe.",
            ))
    return findings


# ---------------------------------------------------------------------------
# GL020 — signal-handler reachability
# ---------------------------------------------------------------------------

@register(
    "GL020",
    "signal-handler-reachable code performs an indefinite lock acquire or "
    "buffered print: the handler may have interrupted the thread holding "
    "that very lock — use the *_from_signal try-acquire surface",
)
def check_signal_reachability(project: Project) -> List[Finding]:
    model = model_for(project)
    findings: List[Finding] = []
    reached = model.signal_reachable()
    for fn in sorted(reached, key=lambda f: (f.module.path, f.lineno)):
        why = reached[fn]
        facts = model.fn_facts.get(fn)
        if facts is None:
            continue
        for acq in facts.acquisitions:
            if not acq.blocking:
                continue
            findings.append(Finding(
                rule="GL020", path=acq.path, lineno=acq.lineno,
                symbol=fn.qualname,
                message=f"indefinite acquire of '{acq.lock.name}' in "
                f"signal-handler-reachable code ({why}): the signal may "
                "have interrupted the thread that holds it — "
                "self-deadlock. Use acquire(timeout=...) and drop on "
                "contention (the *_from_signal discipline).",
            ))
        for site in fn.calls:
            if site.callee == "print":
                findings.append(Finding(
                    rule="GL020", path=fn.module.path, lineno=site.lineno,
                    symbol=fn.qualname,
                    message=f"buffered print() in signal-handler-reachable "
                    f"code ({why}): stdio buffers lock internally — use "
                    "os.write (the echo_from_signal discipline).",
                ))
    return findings


# ---------------------------------------------------------------------------
# GL021 — blocking calls while holding a lock
# ---------------------------------------------------------------------------

@register(
    "GL021",
    "blocking call (Thread.join / Condition.wait on another lock / socket "
    "recv / sleep) while holding a lock: every contending thread stalls "
    "for the full blocking duration",
)
def check_blocking_under_lock(project: Project) -> List[Finding]:
    model = model_for(project)
    findings: List[Finding] = []
    seen: Set[Tuple[str, int, str]] = set()
    for fn, facts in sorted(model.fn_facts.items(),
                            key=lambda kv: (kv[0].module.path, kv[0].lineno)):
        for op in facts.block_ops:
            if not op.held:
                continue
            held = ", ".join(sorted({h.name for h in op.held}))
            key = (op.path, op.lineno, op.kind)
            if key in seen:
                continue
            seen.add(key)
            findings.append(Finding(
                rule="GL021", path=op.path, lineno=op.lineno,
                symbol=fn.qualname,
                message=f"{_BLOCK_KIND_PROSE[op.kind]} ({op.detail}) while "
                f"holding [{held}]: every thread contending for the lock "
                "stalls for the full blocking duration. Move the blocking "
                "call outside the locked region.",
            ))
        for call in facts.held_calls:
            reasons = []
            for callee in model.resolve_callees(fn, call.callee):
                for kind, why in sorted(model.may_block(callee).items()):
                    reasons.append(f"{_BLOCK_KIND_PROSE[kind]} ({why})")
            if not reasons:
                continue
            key = (call.path, call.lineno, "call")
            if key in seen:
                continue
            seen.add(key)
            held = ", ".join(sorted({h.name for h in call.held}))
            findings.append(Finding(
                rule="GL021", path=call.path, lineno=call.lineno,
                symbol=fn.qualname,
                message=f"call to '{call.callee}' may block — "
                f"{'; '.join(reasons)} — while holding [{held}]. Move "
                "the call outside the locked region.",
            ))
    return findings
