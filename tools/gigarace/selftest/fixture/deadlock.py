"""Seeded GL018 violations: a lock-order cycle and a self-deadlock.

``LedgerPair`` takes its two locks in opposite orders from ``flush``
and ``merge`` — two threads entering from different sides deadlock.
``Reentry`` re-acquires a non-reentrant lock it already holds.
``OrderedPair`` is the negative control: same two-lock nesting, one
global order, no finding.
"""

import threading


class LedgerPair:
    def __init__(self):
        self._index_lock = threading.Lock()
        self._journal_lock = threading.Lock()
        self._rows = []

    def flush(self):
        with self._journal_lock:        # journal -> index
            with self._index_lock:
                self._rows.clear()

    def merge(self):
        with self._index_lock:          # index -> journal: the cycle
            with self._journal_lock:
                self._rows.append(0)


class Reentry:
    def __init__(self):
        self._lock = threading.Lock()
        self._spins = 0

    def seeded_self_deadlock(self):
        with self._lock:
            self._lock.acquire()        # already held, non-reentrant
            self._spins += 1
            self._lock.release()


class OrderedPair:
    """Negative control: both methods honor the a-before-b order."""

    def __init__(self):
        self._a_lock = threading.Lock()
        self._b_lock = threading.Lock()
        self._items = []

    def negative_control_push(self, item):
        with self._a_lock:
            with self._b_lock:
                self._items.append(item)

    def negative_control_drain(self):
        with self._a_lock:
            with self._b_lock:
                self._items.clear()
