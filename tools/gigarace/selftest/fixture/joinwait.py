"""Seeded GL021 violations: blocking calls made while holding a lock.

A ``Thread.join()`` and a ``time.sleep()`` inside locked regions, and a
``Condition.wait()`` entered while a DIFFERENT lock is held — every
contending thread stalls for the full blocking duration. The negative
controls do the same blocking calls with no foreign lock held.
"""

import threading
import time


class WorkerPool:
    def __init__(self):
        self._lock = threading.Lock()
        self._worker = threading.Thread(target=_noop)
        self._stopped = False

    def seeded_join_under_lock(self):
        with self._lock:
            self._stopped = True
            self._worker.join()         # join while holding _lock

    def seeded_sleep_under_lock(self):
        with self._lock:
            time.sleep(0.1)             # sleep while holding _lock

    def negative_control_join(self):
        with self._lock:
            self._stopped = True
        self._worker.join()


class TwoPhase:
    def __init__(self):
        self._lock = threading.Lock()
        self._cond = threading.Condition()
        self._ready = False

    def seeded_wait_under_foreign_lock(self):
        with self._lock:
            with self._cond:
                self._cond.wait(timeout=0.1)    # parks holding _lock

    def negative_control_wait(self):
        with self._cond:
            self._cond.wait(timeout=0.1)


def _noop():
    return None
