"""Seeded GL020 violations: signal-reachable blocking acquire + print.

``DrainHook`` installs ``_on_term`` via ``signal.signal``; the handler
calls ``_report``, which makes an indefinite ``with self._lock:``
acquisition — the signal may have interrupted the very thread that
holds it. The handler also calls buffered ``print``. ``BudgetHook``
seeds the same reachability through a ``register_signal_callback``
chain. ``negative_control_from_signal`` is the sanctioned discipline:
try-acquire with a timeout, drop on contention.
"""

import signal
import threading

_CALLBACKS = []


def register_signal_callback(cb):
    """Stand-in for the flight-recorder signal-callback registry."""
    _CALLBACKS.append(cb)


class DrainHook:
    def __init__(self):
        self._lock = threading.Lock()
        self._pending = 0

    def install(self):
        signal.signal(signal.SIGTERM, self._on_term)

    def _on_term(self, signum, frame):
        self._report()
        print("draining")               # buffered stdio in a handler

    def _report(self):
        with self._lock:                # indefinite, signal-reachable
            self._pending += 1

    def negative_control_from_signal(self):
        if not self._lock.acquire(timeout=0.1):
            return
        try:
            self._pending += 1
        finally:
            self._lock.release()


class BudgetHook:
    def __init__(self):
        self._budget_lock = threading.Lock()
        self._spent = 0

    def install(self):
        register_signal_callback(self._on_signal)

    def _on_signal(self):
        with self._budget_lock:         # reachable via the callback chain
            self._spent += 1
