"""Seeded GL019 violations: unguarded touches of a lock-guarded field.

``_counts`` is written under ``_lock`` in ``bump`` but read and mutated
lock-free elsewhere — the data race the rule exists for. The two
annotated fields are the negative controls: ``guarded-by`` with every
touch under the lock, and ``unguarded`` for a declared single-owner
handoff.
"""

import threading


class StatsBoard:
    def __init__(self):
        self._lock = threading.Lock()
        self._counts = {}
        self._guarded_total = 0   # gigarace: guarded-by _lock
        self._handoff = None      # gigarace: unguarded -- set once before the worker starts; single-owner handoff

    def bump(self, key):
        with self._lock:
            self._counts[key] = self._counts.get(key, 0) + 1
            self._guarded_total += 1

    def seeded_unguarded_read(self):
        return dict(self._counts)       # read without the guard

    def seeded_unguarded_clear(self):
        self._counts.clear()            # in-place mutation without it

    def negative_control_guarded_read(self):
        with self._lock:
            return self._guarded_total

    def negative_control_handoff(self):
        return self._handoff
