"""gigarace: lock-discipline and signal-safety analysis (GL018-GL021).

Static dataflow analysis over the library AST — built on gigalint's
walker / graph / waiver machinery — that models every lock the library
creates, the order in which they are acquired, which fields they guard,
and what the SIGTERM chain may reach. The runtime twin
(``gigapath_tpu/obs/locktrace.py``) records *actual* acquisition orders
under ``GIGAPATH_LOCKTRACE=1``; ``python -m tools.gigarace --validate``
asserts the observed relation is covered by the static graph.
"""
