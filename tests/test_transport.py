"""The TCP boundary transport (gigapath_tpu/dist/transport.py): frame
layer, credit/ack parity with the other transports, frame-layer chaos
(corrupt / reorder / torn-connection / delay), reconnect with
handshake-watermark replay, and the restarted-consumer dedup seed.

All loopback sockets, deterministic chaos specs, no sleeps beyond the
channels' own (tiny) retransmit timers — default tier."""

import os
import time

import numpy as np
import pytest

from gigapath_tpu.dist.boundary import BoundaryConfig, EmbeddingChunk
from gigapath_tpu.dist.transport import (
    FrameBuffer,
    FrameError,
    TcpChannelConsumer,
    TcpChannelProducer,
    blob_to_chunk,
    chunk_to_blob,
    encode_frame,
    make_consumer,
    make_producer,
    read_endpoint,
    transport_name,
)
from gigapath_tpu.resilience.chaos import ChaosInjector

CFG = dict(capacity=4, poll_s=0.005, retransmit_s=0.08,
           connect_timeout_s=2.0, backoff_s=0.2)


def _cfg(**over):
    return BoundaryConfig(**{**CFG, **over})


def _chunk(cid, dim=4, slide="s0", producer="w0"):
    rng = np.random.default_rng([7, cid])
    return EmbeddingChunk.build(
        slide, cid, cid * 8, cid * 8 + 8,
        rng.standard_normal((8, dim), dtype=np.float32),
        coords=rng.uniform(0, 100, (8, 2)).astype(np.float32),
        producer=producer,
    )


@pytest.fixture
def channel(tmp_path):
    cons = TcpChannelConsumer(str(tmp_path), _cfg())
    prod = TcpChannelProducer(str(tmp_path), _cfg(), producer="w0")
    yield prod, cons
    prod.close()
    cons.close()


# ---------------------------------------------------------------------------
# frame layer
# ---------------------------------------------------------------------------

class TestFrames:
    def test_roundtrip_and_partial_feed(self):
        frame = encode_frame({"type": "chunk", "seq": 3},
                             chunk_to_blob(_chunk(3)))
        buf = FrameBuffer()
        buf.feed(frame[:11])
        assert buf.frames() == []          # incomplete: nothing yet
        buf.feed(frame[11:])
        [(header, blob)] = buf.frames()
        assert header["seq"] == 3
        chunk = blob_to_chunk(blob)
        assert chunk.seq == 3 and chunk.verify()

    def test_digest_mismatch_skips_frame_keeps_framing(self):
        good = encode_frame({"type": "ack", "seq": 1})
        bad = bytearray(encode_frame({"type": "ack", "seq": 2}))
        bad[-3] ^= 0xFF                    # flip a body byte past the digest
        buf = FrameBuffer()
        buf.feed(bytes(bad) + good)
        frames = buf.frames()
        assert [h["seq"] for h, _ in frames] == [1]
        assert buf.digest_errors == 1      # corrupt frame counted, dropped

    def test_misframed_stream_raises(self):
        buf = FrameBuffer()
        buf.feed(b"XXXX" + b"\x00" * 48)
        with pytest.raises(FrameError):
            buf.frames()

    def test_chunk_blob_matches_dir_layout(self):
        chunk = _chunk(5)
        again = blob_to_chunk(chunk_to_blob(chunk))
        assert again.checksum == chunk.checksum and again.verify()
        np.testing.assert_array_equal(again.payload, chunk.payload)
        np.testing.assert_array_equal(again.coords, chunk.coords)


# ---------------------------------------------------------------------------
# protocol parity with the other transports
# ---------------------------------------------------------------------------

class TestTcpChannel:
    def test_roundtrip_out_of_order_and_ack_credits(self, channel):
        prod, cons = channel
        for cid in (2, 0, 1):
            prod.send(_chunk(cid), timeout=5)
        got = {}
        for _ in range(3):
            chunk = cons.recv(timeout=2)
            assert chunk is not None and chunk.verify()
            cons.ack(chunk.seq)
            got[chunk.seq] = chunk
        assert sorted(got) == [0, 1, 2]
        assert prod.credits() == 4         # acks refunded every credit
        assert prod.unacked_seqs() == []
        assert cons.stats.duplicates == 0 and cons.stats.frame_errors == 0
        assert prod.stats.bytes_sent > 0

    def test_backpressure_event_at_zero_credits(self, tmp_path):
        from gigapath_tpu.obs.runlog import RunLog

        log = RunLog(str(tmp_path / "run.jsonl"), driver="t", echo=False)
        cons = TcpChannelConsumer(str(tmp_path), _cfg(capacity=1))
        prod = TcpChannelProducer(str(tmp_path), _cfg(capacity=1),
                                  producer="w0", runlog=log)
        prod.send(_chunk(0), timeout=5)
        with pytest.raises(TimeoutError):
            prod.send(_chunk(1), timeout=0.05)
        assert prod.stats.backpressure_events == 1
        log.close()
        import json

        events = [json.loads(line) for line in open(log.path)
                  if line.strip()]
        bp = [ev for ev in events if ev.get("kind") == "backpressure"]
        assert bp and bp[0]["credits"] == 0 and bp[0]["capacity"] == 1
        prod.close()
        cons.close()

    def test_endpoint_file_published(self, channel, tmp_path):
        _, cons = channel
        host, port = read_endpoint(str(tmp_path))
        assert host == "127.0.0.1" and port == cons.port


# ---------------------------------------------------------------------------
# frame-layer adversity (the chaos injectors act INSIDE the transport)
# ---------------------------------------------------------------------------

class TestFrameChaos:
    def test_corrupt_frame_dropped_counted_retransmitted(self, tmp_path):
        cons = TcpChannelConsumer(str(tmp_path), _cfg())
        prod = TcpChannelProducer(str(tmp_path), _cfg(), producer="w0",
                                  chaos=ChaosInjector("corrupt_frame@0"))
        prod.send(_chunk(0), timeout=5)
        assert cons.recv(timeout=0.1) is None, "corrupt frame delivered"
        assert cons.stats.frame_errors >= 1
        time.sleep(CFG["retransmit_s"])
        assert prod.pump_retransmits() >= 1
        chunk = cons.recv(timeout=2)
        assert chunk is not None and chunk.seq == 0 and chunk.verify()
        prod.close()
        cons.close()

    def test_reorder_frame_absorbed_by_seq_layer(self, tmp_path):
        cons = TcpChannelConsumer(str(tmp_path), _cfg())
        prod = TcpChannelProducer(str(tmp_path), _cfg(), producer="w0",
                                  chaos=ChaosInjector("reorder_frame@0"))
        prod.send(_chunk(0), timeout=5)
        prod.send(_chunk(1), timeout=5)
        first = cons.recv(timeout=2)
        second = cons.recv(timeout=2)
        assert first.seq == 1 and second.seq == 0  # swapped on the wire
        assert first.verify() and second.verify()
        prod.close()
        cons.close()

    def test_delay_frame_delays_but_delivers(self, tmp_path):
        cons = TcpChannelConsumer(str(tmp_path), _cfg())
        prod = TcpChannelProducer(str(tmp_path), _cfg(), producer="w0",
                                  chaos=ChaosInjector("delay_frame@0:0.05"))
        t0 = time.monotonic()
        prod.send(_chunk(0), timeout=5)
        assert time.monotonic() - t0 >= 0.05
        assert cons.recv(timeout=2).seq == 0
        prod.close()
        cons.close()

    def test_drop_conn_torn_frame_reconnect_replays(self, tmp_path):
        """drop_conn sends HALF the frame then kills the socket: the
        consumer counts the torn tail, the producer reconnects and the
        handshake watermark replays exactly the unacked chunk."""
        cons = TcpChannelConsumer(str(tmp_path), _cfg())
        prod = TcpChannelProducer(str(tmp_path), _cfg(), producer="w0",
                                  chaos=ChaosInjector("drop_conn@0"))
        prod.send(_chunk(0), timeout=5)
        assert cons.recv(timeout=0.1) is None, "torn frame delivered"
        deadline = time.monotonic() + 5
        chunk = None
        while chunk is None and time.monotonic() < deadline:
            prod.pump_retransmits()
            chunk = cons.recv(timeout=0.05)
        assert chunk is not None and chunk.seq == 0 and chunk.verify()
        assert prod.stats.reconnects == 1
        assert cons.stats.frame_errors >= 1  # the torn tail was counted
        assert cons.stats.duplicates == 0    # replayed once, not sprayed
        prod.close()
        cons.close()

    def test_dup_chunk_still_deduped_over_tcp(self, tmp_path):
        cons = TcpChannelConsumer(str(tmp_path), _cfg())
        prod = TcpChannelProducer(str(tmp_path), _cfg(), producer="w0",
                                  chaos=ChaosInjector("dup_chunk@1"))
        prod.send(_chunk(1), timeout=5)
        assert cons.recv(timeout=2).seq == 1
        assert cons.recv(timeout=0.1) is None
        assert cons.stats.duplicates == 1
        prod.close()
        cons.close()


# ---------------------------------------------------------------------------
# reconnect handshake: the ack watermark bounds the replay
# ---------------------------------------------------------------------------

class TestReconnectWatermark:
    def test_restarted_consumer_gets_only_post_watermark_chunks(
            self, tmp_path):
        """The consumer-crash shape at the channel level: chunks the
        dead consumer ACKED (= checkpoint-covered) are never replayed;
        the delivered-but-unacked one is."""
        root = str(tmp_path)
        cons = TcpChannelConsumer(root, _cfg())
        prod = TcpChannelProducer(root, _cfg(), producer="w0")
        prod.send(_chunk(0), timeout=5)
        assert cons.recv(timeout=2).seq == 0
        cons.ack(0)                          # durable at the watermark
        prod.send(_chunk(1), timeout=5)
        assert cons.recv(timeout=2).seq == 1  # delivered, NOT acked
        cons.close()                          # the consumer "dies"

        cons2 = TcpChannelConsumer(root, _cfg(), delivered=[0])
        deadline = time.monotonic() + 5
        chunk = None
        while chunk is None and time.monotonic() < deadline:
            prod.pump_retransmits()
            chunk = cons2.recv(timeout=0.05)
        assert chunk is not None and chunk.seq == 1, (
            "the unacked chunk must be replayed to the restarted consumer"
        )
        assert 0 not in {chunk.seq}, "watermarked chunk must NOT replay"
        assert cons2.recv(timeout=0.1) is None
        assert cons2.stats.duplicates == 0, (
            "the watermark bounded the replay — nothing to dedup"
        )
        prod.close()
        cons2.close()

    def test_seeded_delivered_set_dedups_retransmits(self, tmp_path):
        root = str(tmp_path)
        cons = TcpChannelConsumer(root, _cfg(), delivered=[3])
        prod = TcpChannelProducer(root, _cfg(), producer="w0")
        prod.send(_chunk(3), timeout=5)
        assert cons.recv(timeout=0.2) is None
        assert cons.stats.duplicates == 1
        prod.close()
        cons.close()


# ---------------------------------------------------------------------------
# the factory seam
# ---------------------------------------------------------------------------

class TestTransportSelection:
    def test_default_is_dir(self, monkeypatch):
        monkeypatch.delenv("GIGAPATH_DIST_TRANSPORT", raising=False)
        assert transport_name() == "dir"

    def test_env_and_explicit_selection(self, monkeypatch):
        monkeypatch.setenv("GIGAPATH_DIST_TRANSPORT", "tcp")
        assert transport_name() == "tcp"
        assert transport_name("dir") == "dir"  # explicit (plan) wins

    def test_unknown_transport_is_loud(self):
        with pytest.raises(ValueError, match="known transports"):
            transport_name("carrier-pigeon")

    def test_factory_builds_the_selected_pair(self, tmp_path, monkeypatch):
        from gigapath_tpu.dist.boundary import (
            DirChannelConsumer,
            DirChannelProducer,
        )

        monkeypatch.delenv("GIGAPATH_DIST_TRANSPORT", raising=False)
        assert isinstance(make_producer(str(tmp_path), _cfg()),
                          DirChannelProducer)
        assert isinstance(make_consumer(str(tmp_path), _cfg()),
                          DirChannelConsumer)
        tcp_cons = make_consumer(str(tmp_path / "tcp"), _cfg(),
                                 transport="tcp")
        tcp_prod = make_producer(str(tmp_path / "tcp"), _cfg(),
                                 transport="tcp")
        assert isinstance(tcp_cons, TcpChannelConsumer)
        assert isinstance(tcp_prod, TcpChannelProducer)
        tcp_prod.close()
        tcp_cons.close()


# ---------------------------------------------------------------------------
# transport counters on the bus
# ---------------------------------------------------------------------------

class TestTransportMetrics:
    def test_counters_ride_the_final_metrics_flush(self, tmp_path,
                                                   monkeypatch):
        import json

        from gigapath_tpu.obs.runlog import RunLog

        monkeypatch.delenv("GIGAPATH_METRICS", raising=False)
        log = RunLog(str(tmp_path / "run.jsonl"), driver="t", echo=False)
        cons = TcpChannelConsumer(str(tmp_path), _cfg(), runlog=log)
        prod = TcpChannelProducer(str(tmp_path), _cfg(), producer="w0",
                                  runlog=log,
                                  chaos=ChaosInjector("corrupt_frame@0"))
        prod.send(_chunk(0), timeout=5)
        assert cons.recv(timeout=0.1) is None
        time.sleep(CFG["retransmit_s"])
        prod.pump_retransmits()
        assert cons.recv(timeout=2).seq == 0
        log.run_end(status="ok")
        events = [json.loads(line) for line in open(log.path)
                  if line.strip()]
        finals = [ev for ev in events if ev.get("kind") == "metrics"
                  and ev.get("reason") == "final"]
        assert finals, "no final metrics flush on run_end"
        counters = finals[-1]["counters"]
        assert counters.get("dist.bytes_sent", 0) > 0
        assert counters.get("dist.frame_errors", 0) >= 1
        prod.close()
        cons.close()


# ---------------------------------------------------------------------------
# chaos parser: loud on typos, new injectors parse
# ---------------------------------------------------------------------------

class TestChaosParsing:
    def test_frame_injectors_parse(self):
        c = ChaosInjector("drop_conn@1,delay_frame@2:0.5,corrupt_frame@3,"
                          "reorder_frame@4,kill_consumer@5")
        assert c.drops_conn(1) and not c.drops_conn(1)          # one-shot
        assert c.delay_frame(2) == 0.5 and c.delay_frame(0) == 0.0
        assert c.corrupts_frame(3) and not c.corrupts_frame(3)
        assert c.reorders_frame(4) and not c.reorders_frame(4)
        assert c._kill_consumer_after == 5

    def test_null_chaos_has_the_frame_surface(self):
        from gigapath_tpu.resilience.chaos import NullChaos

        n = NullChaos()
        assert not n.drops_conn(0) and not n.corrupts_frame(0)
        assert not n.reorders_frame(0) and n.delay_frame(0) == 0.0
        assert not n.maybe_kill_consumer(5)

    def test_typoed_spec_is_error_event_plus_raise(self, tmp_path,
                                                   monkeypatch):
        """The satellite: a typo'd GIGAPATH_CHAOS must never be a
        silently clean run — error event on the bus AND the raise."""
        import json

        from gigapath_tpu.obs.runlog import RunLog
        from gigapath_tpu.resilience.chaos import get_chaos

        monkeypatch.setenv("GIGAPATH_CHAOS", "explode_consumer@1")
        log = RunLog(str(tmp_path / "run.jsonl"), driver="t", echo=False)
        with pytest.raises(ValueError, match="unknown injector"):
            get_chaos(log)
        log.close()
        events = [json.loads(line) for line in open(log.path)
                  if line.strip()]
        errors = [ev for ev in events if ev.get("kind") == "error"]
        assert errors and "unknown injector" in errors[0]["error"]

    def test_typoed_spec_raises_without_runlog_too(self, monkeypatch):
        from gigapath_tpu.resilience.chaos import get_chaos

        monkeypatch.setenv("GIGAPATH_CHAOS", "nonsense@9")
        with pytest.raises(ValueError):
            get_chaos()


# ---------------------------------------------------------------------------
# streaming fold state: export/restore is bit-exact
# ---------------------------------------------------------------------------

class TestSessionCheckpoint:
    def test_export_restore_midstream_is_bit_exact(self):
        """Fold half the chunks, export, restore into a FRESH session,
        fold the rest: the embedding equals the uninterrupted run's
        BIT-exact — the consumer-crash-recovery contract at the session
        level."""
        import jax

        from gigapath_tpu.models.classification_head import get_model
        from gigapath_tpu.models.streaming_encoder import (
            StreamingEncoderSession,
        )
        from gigapath_tpu.utils.registry import create_model_from_registry

        n_tiles, chunk_tiles, dim_in = 24, 8, 8
        _, params = get_model(
            input_dim=dim_in, latent_dim=32, feat_layer="1", n_classes=2,
            model_arch="gigapath_slide_enc_tiny", dtype=None,
        )
        inner = create_model_from_registry(
            "gigapath_slide_enc_tiny", in_chans=dim_in, global_pool=False,
            dtype=None,
        )
        rng = np.random.default_rng(0)
        tiles = rng.standard_normal((n_tiles, dim_in), dtype=np.float32)
        coords = rng.uniform(0, 1000, (n_tiles, 2)).astype(np.float32)

        def feed(session, idx):
            a, b = session.tile_bounds[idx]
            session.feed(idx, tiles[a:b], coords[a:b])

        def build():
            return StreamingEncoderSession(
                inner, params["slide_encoder"], n_tiles,
                chunk_tiles=chunk_tiles, all_layer_embed=True,
            )

        straight = build()
        for i in range(straight.n_chunks):
            feed(straight, i)
        want = [np.asarray(e) for e in straight.finalize()]

        first = build()
        feed(first, 0)
        # an out-of-order arrival parks in the frontier buffer and must
        # survive the checkpoint too
        feed(first, 2)
        state = first.export_state()
        # round-trip through host bytes like the real checkpoint does
        state = jax.tree_util.tree_map(np.asarray, state)

        resumed = build()
        resumed.restore_state(state)
        assert resumed.pending() == first.pending()
        feed(resumed, 1)
        got = [np.asarray(e) for e in resumed.finalize()]
        assert len(got) == len(want)
        for g, w in zip(got, want):
            np.testing.assert_array_equal(g, w)

    def test_ckpt_cadence_past_credits_is_loud(self, tmp_path):
        """Deferred acks past the credit window would deadlock the
        fleet: construction must refuse, not hang."""
        from gigapath_tpu.dist.pipeline import default_plan, run_slide_consumer
        from gigapath_tpu.dist.worker import write_plan

        root = str(tmp_path)
        write_plan(root, default_plan(n_tiles=8, chunk_tiles=8, credits=2,
                                      consumer_ckpt_every=5))
        with pytest.raises(ValueError, match="consumer_ckpt_every"):
            run_slide_consumer(root, deadline_s=1.0)
