"""Fault-tolerance layer (gigapath_tpu/resilience): chaos injection,
hardened checkpoints, non-finite guard, serving self-healing (ISSUE 8
acceptance).

The pinned invariants:

- **kill-and-resume parity**: a chaos-injected SIGTERM at step k in a
  real CPU driver run (subprocess — the signal actually kills it),
  then ``resume="auto"``, reproduces the uninterrupted run's final
  params BIT-exact, with no duplicated or skipped optimizer steps and
  zero unexpected retraces;
- **corrupt-checkpoint fallback**: a chaos-corrupted latest checkpoint
  is skipped with an ``anomaly`` event and the scan falls back to the
  previous valid one;
- **non-finite guard**: a chaos-forced NaN step is a zero-update skip
  (params bit-unchanged across it, ``nonfinite_step`` anomaly emitted)
  with zero retraces, and the guard-off step compiles to BYTE-identical
  HLO vs the pre-guard program;
- **poisoned-batch bisection**: one poisoned slide in a serve batch
  fails exactly ONE future; the other slides return parity-correct
  embeddings.

All fault paths are driven by ``GIGAPATH_CHAOS`` — deterministic,
seeded injection, never luck.
"""

import glob
import json
import os
import signal
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gigapath_tpu.obs.runlog import NullRunLog, RunLog, fail_run
from gigapath_tpu.resilience import (
    ChaosError,
    ChaosInjector,
    NullChaos,
    ResilientCheckpointer,
    SkipStepMonitor,
    get_chaos,
    guard_update,
    nonfinite_guard_enabled,
)
from gigapath_tpu.resilience.chaos import corrupt_checkpoint_dir
from gigapath_tpu.serve.health import (
    BreakerOpenError,
    CircuitBreaker,
    DeadlineExceededError,
    LoadSheddedError,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def read_events(path):
    with open(path) as fh:
        return [json.loads(line) for line in fh if line.strip()]


def run_events(out_dir):
    """Events of the newest (non-flight) run file under out_dir/obs."""
    files = [
        p for p in glob.glob(os.path.join(out_dir, "obs", "*.jsonl"))
        if not os.path.basename(p).startswith("flight-")
    ]
    assert files, f"no run files under {out_dir}/obs"
    return read_events(max(files, key=os.path.getmtime))


def events_of(events, kind, **match):
    out = [ev for ev in events if ev.get("kind") == kind]
    for k, v in match.items():
        out = [ev for ev in out if ev.get(k) == v]
    return out


# ---------------------------------------------------------------------------
# chaos spec parsing (the injection grammar is an interface: pin it)
# ---------------------------------------------------------------------------

class TestChaosSpec:
    def test_unset_is_null_and_falsy(self, monkeypatch):
        monkeypatch.delenv("GIGAPATH_CHAOS", raising=False)
        chaos = get_chaos()
        assert isinstance(chaos, NullChaos) and not chaos
        # every consult is a no-op
        assert chaos.batch_fault(0) is None
        assert chaos.poisoned(["a"]) is None
        assert not chaos.corrupts_checkpoint()
        chaos.loader_fault(3)  # does not raise

    def test_spec_round_trip(self, monkeypatch):
        monkeypatch.setenv(
            "GIGAPATH_CHAOS",
            "nan_loss@3,corrupt_batch@5,sigterm@7,fail_loader@2x2,"
            "slow_loader@4:0.0,corrupt_ckpt,poison@slide9,seed=11",
        )
        chaos = get_chaos()
        assert isinstance(chaos, ChaosInjector) and chaos
        assert chaos.batch_fault(3) == "nan"
        assert chaos.batch_fault(5) == "corrupt"
        assert chaos.batch_fault(4) is None
        assert chaos.poisoned(["slide1", "slide9"]) == "slide9"
        assert chaos.poisoned(["slide1"]) is None
        assert chaos.seed == 11
        # fail_loader@2x2: exactly two raises, then heals
        with pytest.raises(ChaosError):
            chaos.loader_fault(2)
        with pytest.raises(ChaosError):
            chaos.loader_fault(2)
        chaos.loader_fault(2)  # healed
        chaos.loader_fault(4)  # slow (0.0s) but no raise
        # corrupt_ckpt fires exactly once per run
        assert chaos.corrupts_checkpoint()
        assert not chaos.corrupts_checkpoint()

    def test_unknown_token_raises(self):
        with pytest.raises(ValueError, match="unknown injector"):
            ChaosInjector("explode@4")

    def test_batch_faults_poison_a_copy(self):
        chaos = ChaosInjector("nan_loss@0,corrupt_batch@1")
        x = np.zeros((4, 4), np.float32)
        nan = chaos.apply_batch_fault("nan", x)
        big = chaos.apply_batch_fault("corrupt", x)
        assert not np.isfinite(nan).all()
        assert np.abs(big).max() >= 1e30
        assert not x.any()  # the original batch is untouched

    def test_corrupt_checkpoint_dir_skips_manifest(self, tmp_path):
        d = tmp_path / "ckpt"
        d.mkdir()
        (d / "manifest.json").write_text("{}")
        (d / "payload.bin").write_bytes(b"\x00" * 64)
        target = corrupt_checkpoint_dir(str(d), seed=0)
        assert os.path.basename(target) == "payload.bin"
        assert (d / "manifest.json").read_text() == "{}"
        assert (d / "payload.bin").read_bytes() != b"\x00" * 64


# ---------------------------------------------------------------------------
# hardened checkpoints: atomic, verified, rotated, resumable
# ---------------------------------------------------------------------------

def _state(step, scale=1.0):
    return {
        "params": {"w": np.full((4,), scale, np.float32)},
        "step": np.asarray(step),
    }


class TestResilientCheckpointer:
    def test_save_restore_round_trip_and_manifest(self, tmp_path):
        ckpt = ResilientCheckpointer(str(tmp_path / "c"))
        path = ckpt.save(3, _state(3, 1.5))
        assert os.path.isdir(path) and ckpt.verify(path)
        # atomic: no tmp dirs survive the rename
        assert not [n for n in os.listdir(ckpt.dir) if n.startswith(".tmp-")]
        state = ckpt.restore(path, _state(0))
        np.testing.assert_array_equal(
            np.asarray(state["params"]["w"]), np.full((4,), 1.5, np.float32)
        )
        # restored leaves are DEVICE arrays: numpy leaves would land in a
        # different pjit cache entry and retrace every shape once after
        # a resume
        assert all(
            isinstance(leaf, jax.Array)
            for leaf in jax.tree_util.tree_leaves(state)
        )

    def test_verify_catches_corruption(self, tmp_path):
        ckpt = ResilientCheckpointer(str(tmp_path / "c"))
        path = ckpt.save(1, _state(1))
        assert ckpt.verify(path)
        corrupt_checkpoint_dir(path, seed=0)
        assert not ckpt.verify(path)

    def test_rotation_keeps_last_k_plus_best(self, tmp_path):
        ckpt = ResilientCheckpointer(str(tmp_path / "c"), keep=2)
        for step in range(1, 6):
            ckpt.save(step, _state(step))
            if step == 2:
                ckpt.mark_best(step, 0.9)
        steps = [s for s, _ in ckpt.checkpoints()]
        # keep-last-2 is {4, 5}; the best pointer pins 2 outside the
        # rotation window
        assert steps == [2, 4, 5]
        assert ckpt.best()["name"] == "ckpt-00000002"

    def test_restore_latest_falls_back_past_corruption(self, tmp_path):
        log = RunLog(str(tmp_path / "run.jsonl"), driver="t", echo=False)
        ckpt = ResilientCheckpointer(str(tmp_path / "c"), runlog=log)
        ckpt.save(1, _state(1, 1.0))
        ckpt.save(2, _state(2, 2.0))
        corrupt_checkpoint_dir(ckpt.path_for(2), seed=0)
        state, step = ckpt.restore_latest(_state(0))
        assert step == 1
        np.testing.assert_array_equal(
            np.asarray(state["params"]["w"]), np.ones((4,), np.float32)
        )
        events = read_events(log.path)
        (anom,) = events_of(events, "anomaly", detector="corrupt_checkpoint")
        assert anom["step"] == 2
        (rec,) = events_of(events, "recovery", action="resume")
        assert rec["step"] == 1 and rec["fallbacks"] == 1

    def test_restore_latest_empty_dir_returns_none(self, tmp_path):
        ckpt = ResilientCheckpointer(str(tmp_path / "c"))
        assert ckpt.restore_latest(_state(0)) is None

    def test_chaos_corrupts_exactly_the_latest(self, tmp_path):
        chaos = ChaosInjector("corrupt_ckpt")
        ckpt = ResilientCheckpointer(str(tmp_path / "c"), chaos=chaos)
        ckpt.save(1, _state(1, 1.0))
        ckpt.save(2, _state(2, 2.0))
        state, step = ckpt.restore_latest(_state(0))
        assert step == 1  # latest was chaos-corrupted, scan fell back

    def test_same_step_resave_keeps_the_valid_checkpoint(self, tmp_path):
        """An emergency save racing the periodic save it just made (same
        step) must NOT destroy-and-rewrite the valid checkpoint: the old
        rmtree-before-rename left a window with no valid latest at all."""
        ckpt = ResilientCheckpointer(str(tmp_path / "c"))
        p1 = ckpt.save(5, _state(5))
        manifest = os.path.join(p1, "manifest.json")
        before = os.stat(manifest).st_mtime_ns
        assert ckpt.save(5, _state(5)) == p1
        assert os.stat(manifest).st_mtime_ns == before  # untouched
        assert ckpt.verify(p1)
        # a CORRUPT same-step checkpoint is fair game for replacement
        corrupt_checkpoint_dir(p1, seed=0)
        assert not ckpt.verify(p1)
        assert ckpt.save(5, _state(5)) == p1
        assert ckpt.verify(p1)

    def test_sigterm_callback_saves_emergency_checkpoint(self, tmp_path):
        """The handler-side half without a real signal (the subprocess
        acceptance test covers real delivery): arming registers with
        obs/flight and the armed callback lands a verified save."""
        log = RunLog(str(tmp_path / "run.jsonl"), driver="t", echo=False)
        ckpt = ResilientCheckpointer(str(tmp_path / "c"), runlog=log)
        armed = ckpt.arm_sigterm_checkpoint(lambda: (7, _state(7)))
        try:
            assert armed and ckpt._sigterm_cb is not None
            # not a graceful claim: the supervisor's kill is honored
            assert ckpt._sigterm_cb(int(signal.SIGTERM)) is False
            assert [s for s, _ in ckpt.checkpoints()] == [7]
            (rec,) = events_of(
                read_events(log.path), "recovery",
                action="emergency_checkpoint",
            )
            assert rec["step"] == 7
        finally:
            ckpt.disarm()
        assert ckpt._sigterm_cb is None


# ---------------------------------------------------------------------------
# non-finite guard: in-graph skip-step, monitor, HLO identity
# ---------------------------------------------------------------------------

class TestNonFiniteGuard:
    def test_guard_selects_old_on_nonfinite_new_on_finite(self):
        old = {"w": jnp.zeros((3,))}
        new = {"w": jnp.ones((3,))}
        grads = {"w": jnp.ones((3,))}

        state, skipped = guard_update(jnp.float32(0.5), grads, old, new)
        np.testing.assert_array_equal(np.asarray(state["w"]), 1.0)
        assert float(skipped) == 0.0

        state, skipped = guard_update(jnp.float32(np.nan), grads, old, new)
        np.testing.assert_array_equal(np.asarray(state["w"]), 0.0)
        assert float(skipped) == 1.0

        bad_grads = {"w": jnp.array([1.0, np.inf, 1.0])}
        state, skipped = guard_update(jnp.float32(0.5), bad_grads, old, new)
        np.testing.assert_array_equal(np.asarray(state["w"]), 0.0)
        assert float(skipped) == 1.0

    def test_guard_adds_zero_retraces(self):
        """Finite and non-finite batches run the SAME program — the
        skip is a data-dependent select, never a recompile."""

        @jax.jit
        def step(loss, grads, old, new):
            return guard_update(loss, grads, old, new)

        old, new = {"w": jnp.zeros((3,))}, {"w": jnp.ones((3,))}
        grads = {"w": jnp.ones((3,))}
        step(jnp.float32(1.0), grads, old, new)
        step(jnp.float32(np.nan), grads, old, new)
        step(jnp.float32(np.inf), grads, old, new)
        assert step._cache_size() == 1

    def test_guard_off_hlo_byte_identical(self):
        """The guard is a host-side CONSTRUCTION choice: guard=False
        compiles to byte-identical HLO vs the pre-guard step. The one
        normalization: ``metadata={...}`` spans (op source_file/line —
        the step body physically moved into ``_make_train_step``, so
        location metadata necessarily differs while the PROGRAM — ops,
        layouts, schedule — must not)."""
        import re

        import optax

        from gigapath_tpu.models.classification_head import get_model
        from gigapath_tpu.train_gigapath import _make_train_step

        model, params = get_model(
            input_dim=16, latent_dim=32, feat_layer="1", n_classes=2,
            model_arch="gigapath_slide_enc_tiny", freeze=False,
            dtype=jnp.bfloat16,
        )
        tx = optax.adamw(1e-3)
        opt_state = tx.init(params)

        # the pre-PR step body, verbatim (named `step` so even the HLO
        # metadata matches — the comparison is BYTE equality)
        @jax.jit
        def step(params, opt_state, x, c, y, rng):
            def loss_fn(p):
                logits = model.apply(
                    {"params": p}, x, c, deterministic=False,
                    rngs={"dropout": rng},
                )
                return optax.softmax_cross_entropy_with_integer_labels(
                    logits, y
                ).mean()

            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss

        args = (
            params, opt_state, jnp.zeros((1, 8, 16)), jnp.zeros((1, 8, 2)),
            jnp.zeros((1,), jnp.int32), jax.random.PRNGKey(0),
        )

        def hlo(fn):
            text = fn.lower(*args).compile().as_text()
            return re.sub(r", metadata={[^}]*}", "", text)

        reference = hlo(step)
        assert hlo(_make_train_step(model, tx, guard=False)) == reference
        # sanity: the guard-ON program is a different one
        assert hlo(_make_train_step(model, tx, guard=True)) != reference

    def test_enabled_flag_semantics(self, monkeypatch):
        monkeypatch.delenv("GIGAPATH_NONFINITE_GUARD", raising=False)
        assert nonfinite_guard_enabled()  # default ON
        monkeypatch.setenv("GIGAPATH_NONFINITE_GUARD", "0")
        assert not nonfinite_guard_enabled()


class TestSignalSafeRunLog:
    def test_event_from_signal_writes_when_uncontended(self, tmp_path):
        log = RunLog(str(tmp_path / "run.jsonl"), driver="t", echo=False)
        assert log.event_from_signal("recovery", action="drain") is not None
        (ev,) = events_of(read_events(log.path), "recovery", action="drain")
        assert ev["action"] == "drain"

    def test_event_from_signal_drops_on_contention_not_deadlocks(
        self, tmp_path
    ):
        """The SIGTERM recovery callbacks run on the main thread, which
        may be suspended INSIDE event() holding the write lock — the
        signal path must try-acquire and drop, never block forever (the
        FlightRecorder.dump_from_signal discipline)."""
        log = RunLog(str(tmp_path / "run.jsonl"), driver="t", echo=False)
        assert log._lock.acquire()
        try:
            assert log.event_from_signal("recovery", action="drain") is None
        finally:
            log._lock.release()

    def test_null_runlog_has_the_signal_surface(self):
        log = NullRunLog(driver="t", echo=False)
        assert log.event_from_signal("recovery", action="x") is None
        log.echo_from_signal("quiet")  # echo=False: no output, no raise


class TestSkipStepMonitor:
    def test_counts_and_orders_rollback_after_m(self, tmp_path):
        log = RunLog(str(tmp_path / "run.jsonl"), driver="t", echo=False)
        mon = SkipStepMonitor(log, rollback_after_skips=3)
        assert mon.observe(0, 0.0) is None
        assert mon.observe(1, 1.0) is None
        assert mon.observe(2, 1.0) is None
        assert mon.observe(3, 1.0) == "rollback"
        # counts PERFORMED rollbacks (the driver reports back), not
        # orders — an order with nothing to restore must not inflate it
        assert mon.skip_count == 3 and mon.rollback_count == 0
        mon.rollback_performed()
        assert mon.rollback_count == 1
        # a finite step resets the consecutive counter
        assert mon.observe(4, 1.0) is None
        assert mon.observe(5, 0.0) is None
        assert mon.observe(6, 1.0) is None
        skips = events_of(read_events(log.path), "recovery",
                          action="skip_step")
        assert [ev["consecutive"] for ev in skips] == [1, 2, 3, 1, 1]

    def test_zero_disables_rollback(self, tmp_path):
        log = RunLog(str(tmp_path / "run.jsonl"), driver="t", echo=False)
        mon = SkipStepMonitor(log, rollback_after_skips=0)
        for i in range(6):
            assert mon.observe(i, 1.0) is None

    def test_rollback_without_checkpoint_is_loud_not_counted(self, tmp_path):
        """An ordered rollback with no checkpoint to restore (the default
        checkpoint_every=0 run) must surface an event, not dissolve into
        a silent no-op counted as a performed rollback."""
        log = RunLog(str(tmp_path / "run.jsonl"), driver="t", echo=False)
        mon = SkipStepMonitor(log, rollback_after_skips=1)
        assert mon.observe(0, 1.0) == "rollback"
        mon.rollback_unavailable(0)
        assert mon.rollback_count == 0
        (ev,) = events_of(read_events(log.path), "recovery",
                          action="rollback_unavailable")
        assert ev["step"] == 0


# ---------------------------------------------------------------------------
# serving self-healing: breaker, shedding, deadlines, bisection
# ---------------------------------------------------------------------------

class TestCircuitBreaker:
    def test_opens_after_n_failures_probes_and_closes(self):
        br = CircuitBreaker(failures=2, cooldown_s=10.0)
        assert br.admit(16, now=0.0) == "dispatch"
        assert br.record_failure(16, now=0.0) is None
        assert br.record_failure(16, now=0.0) == "open"
        assert br.trips == 1
        # open: fail fast until the cooldown elapses
        assert br.admit(16, now=5.0) == "reject"
        assert br.admit(16, now=10.0) == "probe"
        # one probe at a time
        assert br.admit(16, now=10.0) == "reject"
        assert br.record_success(16) == "close"
        assert br.admit(16, now=11.0) == "dispatch"

    def test_failed_probe_reopens_with_fresh_cooldown(self):
        br = CircuitBreaker(failures=1, cooldown_s=10.0)
        assert br.record_failure(16, now=0.0) == "open"
        assert br.admit(16, now=10.0) == "probe"
        assert br.record_failure(16, now=10.0) == "open"
        assert br.trips == 2
        assert br.admit(16, now=15.0) == "reject"
        assert br.admit(16, now=20.0) == "probe"

    def test_buckets_are_independent(self):
        br = CircuitBreaker(failures=1, cooldown_s=10.0)
        assert br.record_failure(16, now=0.0) == "open"
        assert br.admit(32, now=0.0) == "dispatch"

    def test_success_resets_consecutive(self):
        br = CircuitBreaker(failures=2, cooldown_s=10.0)
        br.record_failure(16, now=0.0)
        br.record_success(16)
        assert br.record_failure(16, now=0.0) is None  # back to 1


@pytest.fixture(scope="module")
def tiny_model():
    from gigapath_tpu.models.classification_head import get_model

    # f32: the 1e-5 bisection-parity bar is a float32 statement
    return get_model(
        input_dim=16, latent_dim=32, feat_layer="1", n_classes=2,
        model_arch="gigapath_slide_enc_tiny", dtype=None,
    )


def _forward_fn(model):
    def forward(p, embeds, coords, pad_mask):
        return model.apply({"params": p}, embeds, coords,
                           pad_mask=pad_mask, deterministic=True)

    return forward


def _serve_config(tmp_path, **overrides):
    from gigapath_tpu.serve import ServeConfig

    base = dict(
        max_batch=4, max_wait_s=0.01, bucket_min=16, bucket_growth=2.0,
        bucket_max=64, bucket_align=16, feature_dim=16, artifact_dir=None,
    )
    base.update(overrides)
    return ServeConfig(**base)


def _slides(rng, lengths):
    return [
        (
            f"s{i}_n{n}",
            rng.normal(size=(n, 16)).astype(np.float32),
            rng.uniform(0, 25000, (n, 2)).astype(np.float32),
        )
        for i, n in enumerate(lengths)
    ]


class TestServeSelfHealing:
    def test_poisoned_batch_bisection_isolates_one_future(
        self, tiny_model, rng, tmp_path, monkeypatch
    ):
        """ISSUE 8 acceptance: one poisoned slide in a coalesced batch
        fails exactly ONE future (ChaosError); the other slides return
        embeddings parity-equal to the exact forward."""
        from gigapath_tpu.serve import SlideService

        model, params = tiny_model
        slides = _slides(rng, [5, 7, 9])  # one bucket (16), one batch
        poisoned_id = slides[1][0]
        monkeypatch.setenv("GIGAPATH_CHAOS", f"poison@{poisoned_id}")
        service = SlideService(
            _forward_fn(model), params, config=_serve_config(tmp_path),
            out_dir=str(tmp_path), identity="tiny",
        )
        futs = [service.submit(*s) for s in slides]
        while service.step(drain=True):
            pass
        with pytest.raises(ChaosError):
            futs[1].result(timeout=10)
        for (sid, f, c), fut in zip(slides, futs):
            if sid == poisoned_id:
                continue
            exact = np.asarray(model.apply(
                {"params": params}, f[None], c[None], deterministic=True,
            ), np.float32)[0]
            np.testing.assert_allclose(
                np.asarray(fut.result(timeout=10), np.float32), exact,
                atol=1e-5,
            )
        assert service.poisoned_requests == 1
        assert service.bisections >= 1
        events = read_events(service.runlog.path)
        assert events_of(events, "recovery", action="bisect")
        (poison_ev,) = events_of(events, "recovery",
                                 action="poisoned_request")
        assert poison_ev["slide_id"] == poisoned_id
        # bisection re-dispatches at the same bucket shape: no compile
        # beyond the one bucket's executable
        assert service.aot.compiled_count == 1
        assert service.watchdog.unexpected_retraces == []
        service.close()

    def test_load_shedding_rejects_past_token_budget(
        self, tiny_model, rng, tmp_path, monkeypatch
    ):
        from gigapath_tpu.serve import SlideService

        monkeypatch.delenv("GIGAPATH_CHAOS", raising=False)
        model, params = tiny_model
        service = SlideService(
            _forward_fn(model), params,
            config=_serve_config(tmp_path, shed_tokens=16),
            out_dir=str(tmp_path), identity="tiny",
        )
        a, b = _slides(rng, [5, 7])
        f1 = service.submit(*a)   # 16 padded tokens queued
        f2 = service.submit(*b)   # 16 + 16 > 16 -> shed
        with pytest.raises(LoadSheddedError):
            f2.result(timeout=10)
        assert service.shed_count == 1
        while service.step(drain=True):
            pass
        assert np.isfinite(np.asarray(f1.result(timeout=10))).all()
        (shed_ev,) = events_of(read_events(service.runlog.path),
                               "recovery", action="shed")
        assert shed_ev["budget"] == 16
        service.close()

    def test_shedding_never_rejects_cache_hits_or_joins(
        self, tiny_model, rng, tmp_path, monkeypatch
    ):
        """The shed check runs AFTER the cache/pending probes: a repeat
        of a cached (or in-flight) slide adds zero queue load and must
        be served even when the queue is past the token budget —
        shedding exactly the hot repeated traffic the cache exists for
        would be self-defeating."""
        from gigapath_tpu.serve import SlideService

        monkeypatch.delenv("GIGAPATH_CHAOS", raising=False)
        model, params = tiny_model
        service = SlideService(
            _forward_fn(model), params,
            config=_serve_config(tmp_path, shed_tokens=16),
            out_dir=str(tmp_path), identity="tiny",
        )
        a, b = _slides(rng, [5, 7])
        f1 = service.submit(*a)          # 16 padded tokens queued
        j1 = service.submit(*a)          # identical content: in-flight
        assert j1 is f1                  # join, not shed, at full budget
        while service.step(drain=True):
            pass
        assert np.isfinite(np.asarray(f1.result(timeout=10))).all()
        f2 = service.submit(*b)          # queue empty again: accepted
        h1 = service.submit(*a)          # cached now; queue is at budget
        assert h1.result(timeout=10) is not None  # hit served, not shed
        assert service.shed_count == 0
        while service.step(drain=True):
            pass
        assert np.isfinite(np.asarray(f2.result(timeout=10))).all()
        service.close()

    def test_deadline_fails_expired_requests_at_dispatch(
        self, tiny_model, rng, tmp_path, monkeypatch
    ):
        import time

        from gigapath_tpu.serve import SlideService

        monkeypatch.delenv("GIGAPATH_CHAOS", raising=False)
        model, params = tiny_model
        service = SlideService(
            _forward_fn(model), params,
            config=_serve_config(tmp_path, deadline_s=0.01),
            out_dir=str(tmp_path), identity="tiny",
        )
        (sid, f, c) = _slides(rng, [5])[0]
        fut = service.submit(sid, f, c)
        time.sleep(0.05)  # one-sided: only needs wait > deadline
        while service.step(drain=True):
            pass
        with pytest.raises(DeadlineExceededError):
            fut.result(timeout=10)
        assert service.deadline_failures == 1
        assert events_of(read_events(service.runlog.path), "recovery",
                         action="deadline")
        service.close()

    def test_breaker_trips_probes_and_closes_through_service(
        self, tiny_model, rng, tmp_path, monkeypatch
    ):
        """A persistently failing bucket opens its breaker (later
        batches fail fast), and a half-open probe closes it again once
        the poison clears."""
        from gigapath_tpu.serve import SlideService

        model, params = tiny_model
        slides = _slides(rng, [5, 7, 9])
        monkeypatch.setenv("GIGAPATH_CHAOS", f"poison@{slides[0][0]}")
        service = SlideService(
            _forward_fn(model), params,
            config=_serve_config(
                tmp_path, max_batch=1, breaker_failures=1,
                breaker_cooldown_s=3600.0,
            ),
            out_dir=str(tmp_path), identity="tiny",
        )
        f0 = service.submit(*slides[0])  # poisoned singleton: trips
        while service.step(drain=True):
            pass
        with pytest.raises(ChaosError):
            f0.result(timeout=10)
        assert service.breaker.state(16) == "open"
        f1 = service.submit(*slides[1])  # open breaker: fail fast
        while service.step(drain=True):
            pass
        with pytest.raises(BreakerOpenError):
            f1.result(timeout=10)
        # cooldown elapses -> this dispatch is THE half-open probe; the
        # poison is gone, so success closes the breaker
        service.breaker._entry(16)["opened_at"] = -1e9
        f2 = service.submit(*slides[2])
        while service.step(drain=True):
            pass
        assert np.isfinite(np.asarray(f2.result(timeout=10))).all()
        assert service.breaker.state(16) == "closed"
        events = read_events(service.runlog.path)
        assert events_of(events, "recovery", action="breaker_open")
        assert events_of(events, "recovery", action="breaker_shed")
        assert events_of(events, "recovery", action="breaker_probe")
        assert events_of(events, "recovery", action="breaker_close")
        service.close()

    def test_draining_service_rejects_new_submits(
        self, tiny_model, rng, tmp_path, monkeypatch
    ):
        from gigapath_tpu.serve import SlideService

        monkeypatch.delenv("GIGAPATH_CHAOS", raising=False)
        model, params = tiny_model
        service = SlideService(
            _forward_fn(model), params, config=_serve_config(tmp_path),
            out_dir=str(tmp_path), identity="tiny",
        )
        a, b = _slides(rng, [5, 7])
        f1 = service.submit(*a)
        service._draining = True  # what the SIGTERM chain flips
        with pytest.raises(RuntimeError, match="draining"):
            service.submit(*b)
        while service.step(drain=True):
            pass
        assert np.isfinite(np.asarray(f1.result(timeout=10))).all()
        service.close()

    def test_repeat_sigterm_escalates_past_a_stuck_drain(
        self, tiny_model, tmp_path, monkeypatch
    ):
        """The FIRST SIGTERM claims a graceful drain; a REPEAT is the
        operator escalating past a drain that isn't finishing (hung
        dispatch) and must NOT re-claim — the chain proceeds to the
        prior disposition (process death)."""
        from gigapath_tpu.serve import SlideService

        monkeypatch.delenv("GIGAPATH_CHAOS", raising=False)
        model, params = tiny_model
        service = SlideService(
            _forward_fn(model), params, config=_serve_config(tmp_path),
            out_dir=str(tmp_path), identity="tiny",
        )
        service._arm_signal_drain()
        try:
            assert service._sigterm_cb is not None
            assert service._sigterm_cb(int(signal.SIGTERM)) is True
            assert service._draining
            assert service._sigterm_cb(int(signal.SIGTERM)) is False
        finally:
            service.close()


# ---------------------------------------------------------------------------
# data-loader hardening: bounded same-sample retry, skip with event
# ---------------------------------------------------------------------------

class TestLoaderHardening:
    @pytest.fixture
    def dataset(self, tmp_path, rng, monkeypatch):
        import h5py
        import pandas as pd

        from gigapath_tpu.data.slide_dataset import SlideDataset

        root = tmp_path / "h5_files"
        root.mkdir()
        rows = []
        for i in range(3):
            with h5py.File(root / f"slide_{i}.h5", "w") as f:
                f.create_dataset(
                    "features",
                    data=rng.normal(size=(8, 16)).astype(np.float32),
                )
                f.create_dataset(
                    "coords",
                    data=rng.integers(0, 5000, (8, 2)).astype(np.float32),
                )
            rows.append({"slide_id": f"slide_{i}.svs",
                         "pat_id": f"pat_{i}", "label": ["neg", "pos"][i % 2]})
        cfg = {"setting": "multi_class",
               "label_dict": {"neg": 0, "pos": 1}, "max_tiles": 10}

        def make(retry=3):
            df = pd.DataFrame(rows)
            return SlideDataset(
                df, str(root), splits=df["pat_id"].tolist(),
                task_config=cfg, retry=retry, retry_backoff_s=0.0,
            )

        return make

    def test_transient_failure_heals_within_retry(self, dataset,
                                                  monkeypatch):
        monkeypatch.setenv("GIGAPATH_CHAOS", "fail_loader@1x1")
        ds = dataset(retry=3)
        sample = ds.get_sample_with_try(1)
        assert sample is not None and sample["imgs"].shape == (8, 16)

    def test_exhausted_retries_skip_with_recovery_event(
        self, dataset, tmp_path, monkeypatch
    ):
        monkeypatch.setenv("GIGAPATH_CHAOS", "fail_loader@1x9")
        ds = dataset(retry=2)
        log = RunLog(str(tmp_path / "run.jsonl"), driver="t", echo=False)
        ds.set_runlog(log)
        assert ds.get_sample_with_try(1) is None  # skipped, not raised
        (ev,) = events_of(read_events(log.path), "recovery",
                          action="data_retry")
        assert ev["index"] == 1 and ev["attempts"] == 2
        assert "ChaosError" in ev["error"]
        # the other samples are untouched
        assert ds.get_sample_with_try(0) is not None

    def test_no_chaos_no_runlog_still_works(self, dataset, monkeypatch):
        monkeypatch.delenv("GIGAPATH_CHAOS", raising=False)
        ds = dataset()
        assert ds.get_sample_with_try(2) is not None


# ---------------------------------------------------------------------------
# the shared driver failure tail
# ---------------------------------------------------------------------------

class TestFailRun:
    def test_error_emergency_and_terminal_run_end(self, tmp_path):
        log = RunLog(str(tmp_path / "run.jsonl"), driver="t", echo=False)
        saved = []

        def emergency():
            saved.append(True)
            return str(tmp_path / "emergency")

        fail_run(log, "driver.train", ValueError("boom"),
                 emergency=emergency)
        events = read_events(log.path)
        assert saved == [True]
        (err,) = events_of(events, "error")
        assert err["where"] == "driver.train" and "boom" in err["error"]
        (rec,) = events_of(events, "recovery",
                           action="emergency_checkpoint")
        assert rec["path"].endswith("emergency")
        (end,) = events_of(events, "run_end")
        assert end["status"] == "error"
        # ordering: error first, terminal run_end last
        kinds = [ev["kind"] for ev in events]
        assert kinds.index("error") < kinds.index("run_end")

    def test_broken_emergency_does_not_mask_the_tail(self, tmp_path):
        log = RunLog(str(tmp_path / "run.jsonl"), driver="t", echo=False)

        def broken():
            raise OSError("disk gone")

        fail_run(log, "driver.train", ValueError("boom"), emergency=broken)
        events = read_events(log.path)
        assert not events_of(events, "recovery")
        (end,) = events_of(events, "run_end")
        assert end["status"] == "error"

    def test_null_runlog_is_a_no_op(self):
        fail_run(NullRunLog(driver="t", echo=False), "x", ValueError("y"),
                 emergency=lambda: "p")


# ---------------------------------------------------------------------------
# signal chaining (obs/flight): callbacks after dumps, graceful claims
# ---------------------------------------------------------------------------

class TestSignalCallbacks:
    def test_callbacks_run_after_dumps_and_graceful_claim_wins(
        self, tmp_path, monkeypatch
    ):
        from gigapath_tpu.obs import flight

        order = []
        monkeypatch.setattr(flight, "_SIGNAL_INSTALLED", True)
        monkeypatch.setattr(flight, "_SIGNAL_FLIGHTS", [])
        monkeypatch.setattr(flight, "_SIGNAL_CALLBACKS", [])

        rec = flight.FlightRecorder(
            RunLog(str(tmp_path / "run.jsonl"), driver="t", echo=False)
        )
        real_dump = rec.dump_from_signal
        monkeypatch.setattr(
            rec, "dump_from_signal",
            lambda reason: (order.append("dump"), real_dump(reason))[1],
        )
        flight._SIGNAL_FLIGHTS.append(rec)

        def checkpoint_cb(signum):
            order.append("checkpoint")
            return False

        def drain_cb(signum):
            order.append("drain")
            return True  # graceful claim: the process must NOT die

        assert flight.register_signal_callback(checkpoint_cb)
        assert flight.register_signal_callback(drain_cb)
        # direct handler invocation: the graceful claim returns before
        # the prior disposition (which would kill this pytest process)
        flight._on_sigterm(int(signal.SIGTERM), None)
        assert order == ["dump", "checkpoint", "drain"]

        flight.unregister_signal_callback(checkpoint_cb)
        flight.unregister_signal_callback(drain_cb)
        assert not flight._SIGNAL_CALLBACKS

    def test_broken_callback_is_contained(self, monkeypatch):
        from gigapath_tpu.obs import flight

        monkeypatch.setattr(flight, "_SIGNAL_INSTALLED", True)
        monkeypatch.setattr(flight, "_SIGNAL_FLIGHTS", [])
        monkeypatch.setattr(flight, "_SIGNAL_CALLBACKS", [])
        ran = []

        def broken(signum):
            raise RuntimeError("handler bug")

        def graceful(signum):
            ran.append(True)
            return True

        flight.register_signal_callback(broken)
        flight.register_signal_callback(graceful)
        flight._on_sigterm(int(signal.SIGTERM), None)  # must not raise
        assert ran == [True]


# ---------------------------------------------------------------------------
# MonitorScore persistence (satellite): resumed finetune keeps its best
# ---------------------------------------------------------------------------

class TestMonitorScorePersistence:
    def test_best_score_rides_the_checkpoint(self, tmp_path):
        from gigapath_tpu.utils.checkpoint import MonitorScore

        ckpt = str(tmp_path / "best_ckpt")
        mon = MonitorScore()
        state = {"params": {"w": np.ones((2,), np.float32)}}
        assert mon(0.7, state, ckpt)        # first score always saves
        assert not mon(0.5, state, ckpt)    # worse: no overwrite
        assert mon(0.9, state, ckpt)

        # a NEW process re-arms from the persisted best
        resumed = MonitorScore.from_checkpoint(ckpt)
        assert resumed.best_score == pytest.approx(0.9)
        # the resumed run's first, WORSE epoch cannot overwrite the best
        assert not resumed(0.8, state, ckpt)
        assert resumed(0.95, state, ckpt)

    def test_missing_checkpoint_is_a_fresh_monitor(self, tmp_path):
        from gigapath_tpu.utils.checkpoint import MonitorScore

        mon = MonitorScore.from_checkpoint(str(tmp_path / "nope"))
        assert mon.best_score is None

    def test_sidecar_is_written_and_state_is_the_fallback(self, tmp_path):
        """Re-arming reads the O(1) ``.best.json`` sidecar, not a full
        Orbax restore of the params pytree; a lost sidecar falls back to
        the ``best_score`` persisted inside the checkpoint state."""
        from gigapath_tpu.utils.checkpoint import MonitorScore

        ckpt = str(tmp_path / "best_ckpt")
        mon = MonitorScore()
        assert mon(0.7, {"params": {"w": np.ones((2,), np.float32)}}, ckpt)
        side = MonitorScore._sidecar(ckpt)
        assert os.path.isfile(side)
        os.remove(side)
        resumed = MonitorScore.from_checkpoint(ckpt)
        assert resumed.best_score == pytest.approx(0.7)

    def test_legacy_checkpoint_without_best_score(self, tmp_path):
        from gigapath_tpu.utils.checkpoint import (
            MonitorScore,
            save_checkpoint,
        )

        ckpt = str(tmp_path / "legacy")
        save_checkpoint(ckpt, {"params": {"w": np.ones((2,), np.float32)}})
        mon = MonitorScore.from_checkpoint(ckpt)
        assert mon.best_score is None


# ---------------------------------------------------------------------------
# ISSUE 8 acceptance: the real-driver chaos runs (train_gigapath on CPU)
# ---------------------------------------------------------------------------

_DRIVER = """\
import os, sys
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, {repo!r})
from gigapath_tpu.train_gigapath import train_model
train_model({feature_dir!r}, {labels!r}, {outdir!r}, num_epochs=2,
            latent_dim=32, model_arch="gigapath_slide_enc_tiny",
            feat_layer="1", freeze_pretrained=False, checkpoint_every=2)
print("COMPLETED")
"""


@pytest.fixture(scope="class")
def train_fixture(tmp_path_factory):
    """Cached slide features + labels for train_model: two slides of the
    SAME tile count, so every driver run compiles exactly one step
    executable (retrace accounting stays unambiguous)."""
    from gigapath_tpu.utils.checkpoint import save_checkpoint

    root = tmp_path_factory.mktemp("resilience_driver")
    feature_dir = str(root / "features")
    rng = np.random.default_rng(0)
    rows = []
    for i in range(2):
        sid = f"s{i}"
        save_checkpoint(
            os.path.join(feature_dir, f"{sid}_features"),
            {"features": rng.normal(size=(8, 16)).astype(np.float32),
             "coords": rng.normal(size=(8, 2)).astype(np.float32)},
        )
        rows.append((sid, i % 2))
    labels = str(root / "labels.csv")
    with open(labels, "w") as fh:
        fh.write("slide_id,label\n")
        for sid, lab in rows:
            fh.write(f"{sid},{lab}\n")
    return root, feature_dir, labels


def _train(feature_dir, labels, outdir, **kwargs):
    from gigapath_tpu.train_gigapath import train_model

    base = dict(num_epochs=2, latent_dim=32,
                model_arch="gigapath_slide_enc_tiny", feat_layer="1",
                freeze_pretrained=False, checkpoint_every=2)
    base.update(kwargs)
    return train_model(feature_dir, labels, str(outdir), **base)


def _final_params(outdir):
    from gigapath_tpu.utils.checkpoint import restore_checkpoint

    return restore_checkpoint(os.path.join(str(outdir), "model"))


def _unexpected_retraces(outdir):
    return [ev for ev in run_events(str(outdir))
            if ev["kind"] == "compile" and ev.get("unexpected")]


class TestKillAndResumeAcceptance:
    def test_sigterm_kill_then_resume_is_bit_exact(self, train_fixture,
                                                   monkeypatch):
        """The acceptance chain: (1) uninterrupted baseline; (2) chaos
        SIGTERM after step 1 in a REAL subprocess driver run — the
        handler chain lands an emergency checkpoint, then the process
        dies by the signal; (3) ``resume="auto"`` completes the
        remaining steps; final params match the baseline BIT-exact with
        zero unexpected retraces (no duplicated or skipped optimizer
        steps — any divergence in the rng chain, step cursor or
        opt_state would break float equality)."""
        root, feature_dir, labels = train_fixture
        monkeypatch.delenv("GIGAPATH_CHAOS", raising=False)

        baseline_dir = root / "out-baseline"
        _train(feature_dir, labels, baseline_dir)

        run_dir = root / "out-run"
        env = dict(os.environ)
        env.update({"GIGAPATH_CHAOS": "sigterm@1", "JAX_PLATFORMS": "cpu",
                    "PYTHONPATH": REPO_ROOT})
        script = _DRIVER.format(repo=REPO_ROOT, feature_dir=feature_dir,
                                labels=labels, outdir=str(run_dir))
        proc = subprocess.run(
            [sys.executable, "-c", script], env=env, capture_output=True,
            text=True, timeout=600,
        )
        # killed BY the signal, after the emergency checkpoint landed
        assert "COMPLETED" not in proc.stdout
        assert proc.returncode != 0
        ckpts = glob.glob(os.path.join(str(run_dir), "ckpts", "ckpt-*"))
        assert ckpts, f"no emergency checkpoint; stderr: {proc.stderr[-2000:]}"
        killed_events = run_events(str(run_dir))
        (em,) = events_of(killed_events, "recovery",
                          action="emergency_checkpoint")
        assert em["step"] == 2  # steps 0 and 1 completed, then SIGTERM

        _train(feature_dir, labels, run_dir, resume="auto")
        resumed_events = run_events(str(run_dir))
        (res,) = events_of(resumed_events, "recovery", action="resume")
        assert res["step"] == 2
        assert _unexpected_retraces(run_dir) == []

        base_leaves = jax.tree_util.tree_leaves(_final_params(baseline_dir))
        run_leaves = jax.tree_util.tree_leaves(_final_params(run_dir))
        assert len(base_leaves) == len(run_leaves)
        for a, b in zip(base_leaves, run_leaves):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_corrupt_latest_falls_back_with_anomaly(self, train_fixture,
                                                    monkeypatch):
        """Chaos corrupts the LATEST checkpoint before the resume scan:
        the scan emits a ``corrupt_checkpoint`` anomaly and lands on the
        previous valid one."""
        root, feature_dir, labels = train_fixture
        run_dir = root / "out-corrupt"
        monkeypatch.delenv("GIGAPATH_CHAOS", raising=False)
        _train(feature_dir, labels, run_dir, checkpoint_every=1)

        monkeypatch.setenv("GIGAPATH_CHAOS", "corrupt_ckpt")
        _train(feature_dir, labels, run_dir, resume="auto",
               checkpoint_every=0)
        events = run_events(str(run_dir))
        (anom,) = events_of(events, "anomaly",
                            detector="corrupt_checkpoint")
        assert anom["step"] == 4   # the corrupted latest
        (res,) = events_of(events, "recovery", action="resume")
        assert res["step"] == 3 and res["fallbacks"] == 1


class TestNanStepAcceptance:
    def test_chaos_nan_step_is_skipped_with_zero_retraces(
        self, train_fixture, monkeypatch
    ):
        """A chaos-forced NaN batch becomes a zero-update skip: params
        and opt_state are BIT-unchanged across the skipped step (the
        optimizer count does not advance — no phantom step), the step
        event is tagged, the ``nonfinite_step`` anomaly fires, and the
        whole run pays zero unexpected retraces."""
        root, feature_dir, labels = train_fixture
        run_dir = root / "out-nan"
        monkeypatch.setenv("GIGAPATH_CHAOS", "nan_loss@1")
        result = _train(feature_dir, labels, run_dir, checkpoint_every=1,
                        keep_checkpoints=8)
        assert np.isfinite(result["loss_history"]).all()  # skip excluded

        events = run_events(str(run_dir))
        (nan_step,) = [ev for ev in events
                       if ev["kind"] == "step" and ev.get("nonfinite")]
        assert nan_step["step"] == 1
        assert events_of(events, "anomaly", detector="nonfinite_step")
        (skip,) = events_of(events, "recovery", action="skip_step")
        assert skip["step"] == 1
        assert _unexpected_retraces(run_dir) == []
        (end,) = events_of(events, "run_end")
        assert end["skipped_steps"] == 1 and end["status"] == "ok"

        # ckpt-1 = after step 0 (finite), ckpt-2 = after step 1 (the
        # skip): params and opt_state bit-equal across the skipped step
        ckpt = ResilientCheckpointer(os.path.join(str(run_dir), "ckpts"))
        before, _ = ckpt.restore(ckpt.path_for(1)), 1
        after = ckpt.restore(ckpt.path_for(2))
        for key in ("params", "opt_state"):
            for a, b in zip(jax.tree_util.tree_leaves(before[key]),
                            jax.tree_util.tree_leaves(after[key])):
                np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        # ...but the run kept moving: the NEXT step did update
        third = ckpt.restore(ckpt.path_for(3))
        assert any(
            not np.array_equal(np.asarray(a), np.asarray(b))
            for a, b in zip(jax.tree_util.tree_leaves(after["params"]),
                            jax.tree_util.tree_leaves(third["params"]))
        )

    def test_persistent_nan_rolls_back_to_checkpoint(self, train_fixture,
                                                     monkeypatch):
        root, feature_dir, labels = train_fixture
        run_dir = root / "out-rollback"
        monkeypatch.setenv("GIGAPATH_CHAOS", "nan_loss@1,nan_loss@2")
        monkeypatch.setenv("GIGAPATH_GUARD_ROLLBACK_AFTER", "2")
        result = _train(feature_dir, labels, run_dir, checkpoint_every=1)
        events = run_events(str(run_dir))
        (rb,) = events_of(events, "recovery", action="rollback")
        assert rb["step"] == 2  # second consecutive skip ordered it
        # the rollback's internal checkpoint scan must NOT telemetry a
        # "resume" — this run was never killed and resumed
        assert events_of(events, "recovery", action="resume") == []
        (end,) = events_of(events, "run_end")
        assert end["skipped_steps"] == 2 and end["rollbacks"] == 1
        assert np.isfinite(result["loss_history"]).all()
