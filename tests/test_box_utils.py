import numpy as np
import pytest

from gigapath_tpu.data.box_utils import Box, get_bounding_box


def test_box_validation():
    with pytest.raises(ValueError):
        Box(0, 0, 0, 5)
    with pytest.raises(ValueError):
        Box(0, 0, 5, -1)


def test_box_algebra():
    b = Box(2, 3, 4, 5)
    assert b + (1, -1) == Box(3, 2, 4, 5)
    assert b * 2 == Box(4, 6, 8, 10)
    assert 2 * b == Box(4, 6, 8, 10)
    assert b / 2 == Box(1, 1, 2, 2)
    assert b.add_margin(1) == Box(1, 2, 6, 7)


def test_box_clip():
    a = Box(0, 0, 10, 10)
    b = Box(5, 5, 10, 10)
    assert a.clip(b) == Box(5, 5, 5, 5)
    assert a.clip(Box(20, 20, 5, 5)) is None


def test_box_slices_roundtrip():
    b = Box(2, 3, 4, 5)
    assert Box.from_slices(b.to_slices()) == b
    arr = np.zeros((10, 10))
    arr[b.to_slices()] = 1
    assert arr.sum() == b.w * b.h


def test_get_bounding_box():
    mask = np.zeros((10, 12))
    mask[3:7, 2:9] = 1
    assert get_bounding_box(mask) == Box(x=2, y=3, w=7, h=4)
    with pytest.raises(RuntimeError):
        get_bounding_box(np.zeros((4, 4)))
    with pytest.raises(TypeError):
        get_bounding_box(np.zeros((4, 4, 4)))
