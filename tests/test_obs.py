"""Observability subsystem: RunLog events, watchdog, heartbeat, report.

The load-bearing contracts from ISSUE 2's acceptance criteria:

- a forced stall produces a ``stall`` event (the axon-tunnel-hang
  defense is actually armed);
- an instrumented step function compiles exactly as many times as the
  uninstrumented one across two buckets (telemetry adds NO retraces);
- ``scripts/obs_report.py`` renders throughput / compile-share / retrace
  sections from a real run's JSONL (the finetune smoke test's run in the
  slow tier; a watchdog-produced run in the default tier).
"""

import io
import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gigapath_tpu.obs import (
    EVENT_KINDS,
    SCHEMA_VERSION,
    CompileWatchdog,
    Heartbeat,
    NullRunLog,
    RunLog,
    get_ledger,
    get_run_log,
    span,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO_ROOT, "scripts"))

import obs_report  # noqa: E402


def read_events(path):
    with open(path) as fh:
        return [json.loads(line) for line in fh if line.strip()]


# ---------------------------------------------------------------------------
# RunLog
# ---------------------------------------------------------------------------

class TestRunLog:
    def test_schema_versioned_events(self, tmp_path):
        path = str(tmp_path / "run.jsonl")
        log = RunLog(path, driver="test", echo=False)
        log.run_start(config={"lr": 1e-4, "name": "t"}, probe_devices=False)
        log.step(0, wall_s=0.25, synced=True, loss=1.5)
        log.eval_event(0, auroc=0.9)
        log.run_end(status="ok")
        events = read_events(path)
        assert [ev["kind"] for ev in events] == [
            "run_start", "step", "eval", "run_end",
        ]
        for ev in events:
            assert ev["v"] == SCHEMA_VERSION
            assert ev["run"] == log.run_id
            assert isinstance(ev["t"], float)
            assert ev["kind"] in EVENT_KINDS
        assert events[0]["config"] == {"lr": 1e-4, "name": "t"}
        assert events[0]["jax_version"] == jax.__version__
        assert events[1] == {**events[1], "step": 0, "wall_s": 0.25,
                             "synced": True, "loss": 1.5}
        assert events[-1]["status"] == "ok" and events[-1]["wall_s"] >= 0

    def test_device_scalars_become_floats(self, tmp_path):
        path = str(tmp_path / "run.jsonl")
        log = RunLog(path, driver="test", echo=False)
        log.step(1, loss=jnp.float32(2.5), grad_norm=jnp.ones(())[None])
        (ev,) = read_events(path)
        assert ev["loss"] == 2.5 and ev["grad_norm"] == 1.0

    def test_writes_survive_close_and_threads(self, tmp_path):
        import threading

        path = str(tmp_path / "run.jsonl")
        log = RunLog(path, driver="test", echo=False)
        threads = [
            threading.Thread(target=lambda i=i: log.step(i)) for i in range(8)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        log.close()
        log.step(99)  # post-close: swallowed, not a crash
        events = read_events(path)
        assert sorted(ev["step"] for ev in events) == list(range(8))

    def test_echo_single_format_includes_wall_and_step(self, capsys):
        log = NullRunLog(driver="finetune")
        log.echo("Loss: 1.0", step=40)
        out = capsys.readouterr().out
        assert out.startswith("[finetune +")
        assert "s step 40] Loss: 1.0" in out

    def test_null_runlog_accepts_every_call_shape(self, capsys):
        null = NullRunLog(driver="bench")
        null.run_start(config={"a": 1}, probe_devices=False)
        null.step(0, wall_s=0.1, synced=True)
        null.compile_event("fn", (1, 2), 0.5, count=1, unexpected=False)
        null.eval_event(0, auroc=1.0)
        null.heartbeat(last_step=0)
        null.stall(last_step=0, since_progress_s=1.0, deadline_s=0.5)
        null.error("here", ValueError("x"))
        null.run_end(status="ok", value=1)
        null.close()
        null.echo("still prints")  # opt-out never silences the console
        assert "still prints" in capsys.readouterr().out


class TestGetRunLog:
    def test_env_opt_out(self, tmp_path, monkeypatch):
        monkeypatch.setenv("GIGAPATH_OBS", "0")
        log = get_run_log("t", out_dir=str(tmp_path))
        assert isinstance(log, NullRunLog) and not isinstance(log, RunLog)
        assert not os.path.exists(tmp_path / "obs")

    def test_default_on_writes_run_start(self, tmp_path, monkeypatch):
        monkeypatch.delenv("GIGAPATH_OBS", raising=False)
        log = get_run_log("t", out_dir=str(tmp_path), echo=False,
                          probe_devices=False)
        assert isinstance(log, RunLog)
        assert os.path.dirname(log.path) == str(tmp_path / "obs")
        events = read_events(log.path)
        assert events[0]["kind"] == "run_start"
        assert events[0]["driver"] == "t"
        log.close()

    def test_obs_dir_env_fallback(self, tmp_path, monkeypatch):
        monkeypatch.delenv("GIGAPATH_OBS", raising=False)
        monkeypatch.setenv("GIGAPATH_OBS_DIR", str(tmp_path / "central"))
        log = get_run_log("t", echo=False, probe_devices=False)
        assert str(tmp_path / "central") == os.path.dirname(log.path)
        log.close()

    def test_shared_run_id_pins_multihost_merge_key(self, tmp_path, monkeypatch):
        """GIGAPATH_OBS_RUN_ID: every rank logs under ONE run id (the
        obs_report merge key) while writing its own per-process file —
        the suffix is host+pid, NOT the rank, so get_run_log never
        touches the backend at driver start (and containerized ranks
        that all run as pid 1 still get distinct files)."""
        monkeypatch.delenv("GIGAPATH_OBS", raising=False)
        monkeypatch.setenv("GIGAPATH_OBS_RUN_ID", "mh-run-1")
        log = get_run_log("t", out_dir=str(tmp_path), echo=False,
                          probe_devices=False)
        assert log.run_id == "mh-run-1"
        base = os.path.basename(log.path)
        assert base.startswith("mh-run-1-")
        assert base.endswith(f"-p{os.getpid()}.jsonl")
        events = read_events(log.path)
        assert events[0]["run"] == "mh-run-1"
        log.close()


# ---------------------------------------------------------------------------
# CompileWatchdog
# ---------------------------------------------------------------------------

class TestCompileWatchdog:
    def test_wrap_counts_one_compile_per_shape(self, tmp_path):
        path = str(tmp_path / "run.jsonl")
        log = RunLog(path, driver="t", echo=False)
        fn = jax.jit(lambda x: x * 2)
        wd = CompileWatchdog("fn", log)
        wrapped = wd.wrap(fn)
        for _ in range(3):
            wrapped(jnp.ones((2, 8)))
        wrapped(jnp.ones((2, 16)))
        compiles = [ev for ev in read_events(path) if ev["kind"] == "compile"]
        assert len(compiles) == 2
        assert all(not ev["unexpected"] for ev in compiles)
        assert len(wd.first_call_sec) == 2
        assert wd.compile_seconds_total() > 0

    def test_unexpected_retrace_flagged(self, tmp_path):
        """Cache growth on an already-seen key = silent retrace, flagged."""
        path = str(tmp_path / "run.jsonl")
        log = RunLog(path, driver="t", echo=False)
        fn = jax.jit(lambda x: x + 1)
        wd = CompileWatchdog("fn", log)
        # key_fn collapses all shapes to one key: the second (different)
        # shape recompiles under a key the watchdog saw as compiled
        wrapped = wd.wrap(fn, key_fn=lambda *a, **k: "constant")
        wrapped(jnp.ones((4,)))
        wrapped(jnp.ones((8,)))
        compiles = [ev for ev in read_events(path) if ev["kind"] == "compile"]
        assert [ev["unexpected"] for ev in compiles] == [False, True]
        assert wd.unexpected_retraces == ["constant"]
        assert "unexpected" in wd.summary()

    def test_bucket_surface_matches_old_compile_log(self):
        """The BucketCompileLog-shaped surface the finetune loop drives."""
        wd = CompileWatchdog("train_step")
        assert wd.is_new((1, 128))
        wd.record((1, 128), 1.25)
        assert not wd.is_new((1, 128))
        wd.record((1, 128), None)  # steady, untimed
        wd.record((1, 128), 0.01)  # steady, timed
        wd.record((1, 256), 0.75)
        summary = wd.summary()
        assert "compile 1.25s" in summary and "compile 0.75s" in summary

    def test_zero_retrace_overhead_parity(self):
        """ISSUE acceptance: the instrumented step compiles exactly as many
        times as the uninstrumented one across two buckets."""

        def step(params, x):
            return params["w"] * jnp.sum(x), {"norm": jnp.sum(x**2)}

        params = {"w": jnp.float32(2.0)}
        buckets = [jnp.ones((1, 128)), jnp.ones((1, 256))]

        bare = jax.jit(step)
        for x in buckets * 3:
            bare(params, x)

        instrumented = jax.jit(step)
        wd = CompileWatchdog("step", fn=instrumented)
        wrapped = wd.wrap(instrumented)
        for x in buckets * 3:
            wrapped(params, x)

        assert bare._cache_size() == instrumented._cache_size() == 2
        assert sum(wd.compile_count.values()) == 2
        assert wd.unexpected_retraces == []


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------

class TestSpans:
    def test_nested_spans_emit_path_depth_duration(self, tmp_path):
        path = str(tmp_path / "run.jsonl")
        log = RunLog(path, driver="t", echo=False)
        with span("epoch", log, epoch=0):
            with span("step", log) as sp:
                sp.note(bucket="(1, 128)")
        events = read_events(path)
        # inner span closes first
        assert [ev["name"] for ev in events] == ["step", "epoch"]
        step, epoch = events
        assert step["path"] == "epoch/step" and step["depth"] == 2
        assert epoch["path"] == "epoch" and epoch["depth"] == 1
        assert step["bucket"] == "(1, 128)" and epoch["epoch"] == 0
        assert step["dur_s"] >= 0 and epoch["dur_s"] >= step["dur_s"]
        assert step["rank"] == 0 and step["fenced"] is False
        assert step["status"] == "ok"
        log.close()

    def test_fence_blocks_and_exposes_dur(self, tmp_path):
        log = RunLog(str(tmp_path / "run.jsonl"), driver="t", echo=False)
        fn = jax.jit(lambda x: (x * 2).sum())
        with span("step", log, fence=True) as sp:
            out = sp.fence(fn(jnp.ones((4,))))
        assert float(out) == 8.0
        assert sp.dur_s is not None and sp.dur_s >= 0
        (ev,) = read_events(log.path)
        assert ev["fenced"] is True and ev["dur_s"] == sp.dur_s
        log.close()

    def test_fence_value_passed_directly(self, tmp_path):
        log = RunLog(str(tmp_path / "run.jsonl"), driver="t", echo=False)
        x = jnp.ones((4,))
        with span("sync", log, fence=x):
            pass
        (ev,) = read_events(log.path)
        assert ev["fenced"] is True
        log.close()

    def test_fence_failure_still_emits_span_event(self, tmp_path, monkeypatch):
        """A device error surfacing at the fence sync must not eat the
        span event (the obs layer exists for the failure moment) and must
        not raise a NEW exception the unfenced driver would never see."""
        log = RunLog(str(tmp_path / "run.jsonl"), driver="t", echo=False)

        def boom(_):
            raise RuntimeError("device exploded at sync")

        monkeypatch.setattr(jax, "block_until_ready", boom)
        with span("step", log, fence=True) as sp:
            sp.fence(jnp.ones(2))
        (ev,) = read_events(log.path)
        assert ev["status"] == "error"
        assert "device exploded" in ev["fence_error"]
        assert sp.dur_s is not None
        log.close()

    def test_error_status_recorded_and_reraised(self, tmp_path):
        log = RunLog(str(tmp_path / "run.jsonl"), driver="t", echo=False)
        with pytest.raises(ValueError):
            with span("boom", log):
                raise ValueError("x")
        (ev,) = read_events(log.path)
        assert ev["status"] == "error" and ev["dur_s"] >= 0
        log.close()

    def test_caller_fields_cannot_shadow_span_schema(self, tmp_path):
        log = RunLog(str(tmp_path / "run.jsonl"), driver="t", echo=False)
        with span("eval", log, status="pending", depth=42):
            pass
        (ev,) = read_events(log.path)
        assert ev["status"] == "ok" and ev["depth"] == 1  # schema wins
        assert ev["field_status"] == "pending" and ev["field_depth"] == 42
        log.close()

    def test_rank_is_an_explicit_override_not_a_field(self, tmp_path):
        """``rank`` graduated from shadowable free-form field to a named
        span parameter (the dist worker processes tag spans with their
        WORKER index — jax process index is 0 for every group on one
        machine). Default stays the process index."""
        log = RunLog(str(tmp_path / "run.jsonl"), driver="t", echo=False)
        with span("chunk", log, rank=3):
            pass
        with span("chunk", log):
            pass
        first, second = read_events(log.path)
        assert first["rank"] == 3 and "field_rank" not in first
        assert second["rank"] == 0
        log.close()

    def test_null_runlog_is_true_noop(self):
        null = NullRunLog(driver="t", echo=False)
        with span("step", null, fence=True) as sp:
            sp.fence(jnp.ones(2))
            sp.note(a=1)
        assert sp.dur_s is None  # no clock reads, no event, no fence
        with span("bare", None) as sp2:
            pass
        assert sp2.dur_s is None


# ---------------------------------------------------------------------------
# zero-overhead contracts (ISSUE 4 acceptance)
# ---------------------------------------------------------------------------

class TestZeroOverhead:
    def test_obs_off_spans_and_ledger_add_zero_retraces_and_no_files(
        self, tmp_path, monkeypatch
    ):
        """GIGAPATH_OBS=0: the fully instrumented loop (runlog + watchdog
        + ledger + fenced spans) compiles exactly as often as the bare
        loop and leaves NOTHING on disk."""
        monkeypatch.setenv("GIGAPATH_OBS", "0")

        def step(params, x):
            return params["w"] * jnp.sum(x)

        params = {"w": jnp.float32(2.0)}
        buckets = [jnp.ones((1, 128)), jnp.ones((1, 256))]

        bare = jax.jit(step)
        for x in buckets * 3:
            bare(params, x)

        runlog = get_run_log("t", out_dir=str(tmp_path))
        ledger = get_ledger(runlog)
        instrumented = jax.jit(step)
        wd = CompileWatchdog("step", runlog, fn=instrumented, ledger=ledger)
        wrapped = wd.wrap(instrumented)
        for i, x in enumerate(buckets * 3):
            with span("step", runlog, fence=True) as sp:
                out = sp.fence(wrapped(params, x))
            runlog.step(i, wall_s=sp.dur_s, synced=True, loss=float(out))
        runlog.run_end(status="ok", ledger_path=ledger.path)

        assert bare._cache_size() == instrumented._cache_size() == 2
        assert sum(wd.compile_count.values()) == 2
        assert wd.unexpected_retraces == []
        assert list(tmp_path.iterdir()) == [], "obs-off run left artifacts"

    def test_obs_on_instrumented_hlo_is_identical(self, tmp_path):
        """With obs ON, watching + ledgering a function must not alter
        its traced program: the compiled HLO of the watched function is
        byte-identical to an unwatched twin, and no extra call-cache
        entries appear."""

        def step(params, x):
            return params["w"] * jnp.sum(x)

        params = {"w": jnp.float32(2.0)}
        x = jnp.ones((1, 128))

        bare = jax.jit(step)
        bare(params, x)

        log = RunLog(str(tmp_path / "run.jsonl"), driver="t", echo=False)
        ledger = get_ledger(log)
        watched = jax.jit(step)
        wd = CompileWatchdog("step", log, fn=watched, ledger=ledger)
        wrapped = wd.wrap(watched)
        with span("step", log, fence=True) as sp:
            sp.fence(wrapped(params, x))
        assert len(ledger.entries) == 1  # the profile was captured

        assert watched._cache_size() == bare._cache_size() == 1
        hlo_bare = bare.lower(params, x).compile().as_text()
        hlo_watched = watched.lower(params, x).compile().as_text()
        assert hlo_bare == hlo_watched
        log.close()


# ---------------------------------------------------------------------------
# in-graph telemetry
# ---------------------------------------------------------------------------

class TestTelemetry:
    def test_step_scalars_inside_jit(self):
        from gigapath_tpu.obs.telemetry import step_scalars

        @jax.jit
        def step(params, x):
            loss = (params["w"] * x).sum()
            grads = jax.grad(lambda p: (p["w"] * x).sum())(params)
            return step_scalars(loss=loss, grads=grads, params=params,
                                extra=jnp.float32(3.0))

        out = step({"w": jnp.full((4,), 2.0)}, jnp.ones((4,)))
        assert set(out) == {"loss", "grad_norm", "param_norm", "extra"}
        assert float(out["loss"]) == 8.0
        assert float(out["grad_norm"]) == pytest.approx(2.0)  # ||[1,1,1,1]||
        assert float(out["param_norm"]) == pytest.approx(4.0)
        assert float(out["extra"]) == 3.0

    def test_tree_norm_empty_and_bf16(self):
        from gigapath_tpu.obs.telemetry import tree_norm

        assert float(tree_norm({})) == 0.0
        # bf16 leaves accumulate in fp32
        n = tree_norm({"a": jnp.full((256,), 0.01, jnp.bfloat16)})
        assert float(n) == pytest.approx(0.16, rel=0.05)

    def test_moe_scalars_matches_host_collector_keys(self, rng):
        from gigapath_tpu.obs.telemetry import moe_scalars
        from gigapath_tpu.ops.moe.moe_layer import MOELayer
        from gigapath_tpu.utils.profiling import collect_moe_metadata

        layer = MOELayer(embed_dim=16, ffn_dim=32, num_experts=4, top1=True)
        x = jnp.asarray(rng.normal(size=(1, 8, 16)), jnp.float32)
        params = layer.init(jax.random.PRNGKey(0), x)["params"]
        _, mods = layer.apply({"params": params}, x, mutable=["intermediates"])
        in_graph = moe_scalars(mods["intermediates"])
        host = collect_moe_metadata(mods["intermediates"])
        assert set(host) <= set(in_graph)
        for k, v in host.items():
            assert float(np.asarray(in_graph[k]).reshape(())) == pytest.approx(v)


# ---------------------------------------------------------------------------
# Heartbeat / stall
# ---------------------------------------------------------------------------

class TestHeartbeat:
    def test_forced_stall_emits_stall_event(self, tmp_path):
        path = str(tmp_path / "run.jsonl")
        log = RunLog(path, driver="t", echo=False)
        with Heartbeat(log, interval_s=0.05, stall_after_s=0.15, name="t") as hb:
            hb.beat(7)
            time.sleep(0.5)  # no further beats: exceed the deadline
        kinds = [ev["kind"] for ev in read_events(path)]
        assert "stall" in kinds
        assert "heartbeat" in kinds
        stall = next(ev for ev in read_events(path) if ev["kind"] == "stall")
        assert stall["last_step"] == 7
        assert stall["since_progress_s"] >= 0.15
        assert stall["deadline_s"] == 0.15
        assert hb.stall_count == 1

    def test_steady_beats_prevent_stall(self, tmp_path):
        path = str(tmp_path / "run.jsonl")
        log = RunLog(path, driver="t", echo=False)
        with Heartbeat(log, interval_s=0.05, stall_after_s=0.3, name="t") as hb:
            for i in range(8):
                hb.beat(i)
                time.sleep(0.05)
        events = read_events(path)
        assert not any(ev["kind"] == "stall" for ev in events)
        assert any(ev["kind"] == "heartbeat" for ev in events)

    def test_recovery_rearms_stall_detection(self, tmp_path):
        path = str(tmp_path / "run.jsonl")
        log = RunLog(path, driver="t", echo=False)
        with Heartbeat(log, interval_s=10.0, stall_after_s=0.12, name="t") as hb:
            time.sleep(0.3)   # first stall
            hb.beat(1)        # recovery
            time.sleep(0.3)   # second stall episode
        stalls = [ev for ev in read_events(path) if ev["kind"] == "stall"]
        assert len(stalls) == 2


# ---------------------------------------------------------------------------
# obs_report
# ---------------------------------------------------------------------------

def _render(paths, run=None):
    buf = io.StringIO()
    events = []
    for p in paths:
        events.extend(obs_report.load_events(p, run_id=run))
    events.sort(key=lambda ev: ev.get("t", 0.0))
    rc = obs_report.render(events, out=buf)
    return rc, buf.getvalue()


class TestObsReport:
    def test_report_from_instrumented_jit_run(self, tmp_path):
        """Default-tier sibling of the finetune-smoke report test: a real
        jitted fn drives the watchdog + runlog, and the report renders
        throughput, compile-share and retrace sections from the file."""
        path = str(tmp_path / "run.jsonl")
        log = RunLog(path, driver="t", echo=False)
        log.run_start(config={"purpose": "report test"}, probe_devices=False)
        fn = jax.jit(lambda x: (x * 2).sum())
        wd = CompileWatchdog("step", log)
        wrapped = wd.wrap(fn)
        for i in range(12):
            x = jnp.ones((1, 128 if i % 2 == 0 else 256))
            t0 = time.time()
            wrapped(x)
            log.step(i, wall_s=time.time() - t0, synced=True, loss=1.0 / (i + 1))
        log.run_end(status="ok")

        rc, text = _render([path])
        assert rc == 0
        assert "== throughput ==" in text and "p50" in text
        assert "== compile ==" in text and "% of run wall" in text
        assert "retrace table" in text
        assert "steps: 12" in text

    def test_selftest_passes(self):
        assert obs_report.selftest() == 0

    def test_cli_on_missing_file_exits_2(self):
        assert obs_report.main(["/nonexistent/run.jsonl"]) == 2

    def test_run_filter_on_multi_run_stream(self, tmp_path):
        path = str(tmp_path / "stream.jsonl")
        a = RunLog(path, driver="bench", run_id="run-a", echo=False)
        a.step(0, wall_s=0.1, synced=True)
        a.close()
        b = RunLog(path, driver="bench", run_id="run-b", echo=False)
        b.step(0, wall_s=0.2, synced=True)
        b.close()
        rc, text = _render([path], run="run-a")
        assert rc == 0
        assert "run-a" in text and "run-b" not in text


@pytest.mark.slow
def test_obs_report_on_finetune_smoke(tmp_path, rng):
    """ISSUE acceptance: the finetune smoke test's own run JSONL renders a
    report with throughput, compile-share and retrace sections."""
    import glob

    import h5py
    import pandas as pd

    from gigapath_tpu.finetune.main import main

    root = tmp_path / "h5_files"
    root.mkdir()
    rows = []
    for i in range(8):
        n_tiles = 12 + i
        with h5py.File(root / f"s{i}.h5", "w") as f:
            f.create_dataset(
                "features", data=rng.normal(size=(n_tiles, 16)).astype(np.float32)
            )
            f.create_dataset(
                "coords", data=rng.integers(0, 2000, (n_tiles, 2)).astype(np.float32)
            )
        rows.append(
            {"slide_id": f"s{i}.svs", "pat_id": f"p{i}", "label": ["neg", "pos"][i % 2]}
        )
    csv_path = tmp_path / "dataset.csv"
    pd.DataFrame(rows).to_csv(csv_path, index=False)
    yaml_path = tmp_path / "task.yaml"
    yaml_path.write_text(
        "name: toy\nsetting: multi_class\n"
        "label_dict:\n  neg: 0\n  pos: 1\nmax_tiles: 64\nshuffle_tiles: false\n"
    )
    save_dir = str(tmp_path / "out")
    main(
        [
            "--task_cfg_path", str(yaml_path),
            "--dataset_csv", str(csv_path),
            "--root_path", str(root),
            "--split_dir", str(tmp_path / "splits"),
            "--save_dir", save_dir,
            "--model_arch", "gigapath_slide_enc_tiny",
            "--input_dim", "16",
            "--latent_dim", "32",
            "--feat_layer", "1",
            "--folds", "1",
            "--epochs", "1",
            "--warmup_epochs", "1",
            "--gc", "2",
            "--val_r", "0.25",
            "--model_select", "val",
            "--report_to", "jsonl",
            "--dropout", "0.0",
            "--drop_path_rate", "0.0",
        ]
    )
    runs = glob.glob(os.path.join(save_dir, "**", "obs", "*.jsonl"), recursive=True)
    assert runs, "the finetune run must leave an obs JSONL artifact"
    rc, text = _render([runs[0]])
    assert rc == 0
    events = read_events(runs[0])
    kinds = {ev["kind"] for ev in events}
    assert {"run_start", "step", "compile", "eval", "run_end"} <= kinds
    # in-graph scalars rode the synced step events or epoch telemetry
    assert "== throughput ==" in text
    assert "== compile ==" in text and "retrace table" in text
    assert "== timeline ==" in text
