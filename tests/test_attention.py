import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gigapath_tpu.ops.attention import MultiheadAttention, attention_with_lse


def _np_attention(q, k, v, causal=False):
    """Independent numpy oracle."""
    B, Lq, H, D = q.shape
    Lk = k.shape[1]
    logits = np.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(D)
    if causal:
        mask = np.triu(np.ones((Lq, Lk), bool), k=1 + (Lk - Lq))
        logits = np.where(mask, -1e8, logits)
    m = logits.max(-1, keepdims=True)
    e = np.exp(logits - m)
    lse = np.log(e.sum(-1)) + m[..., 0]
    p = e / e.sum(-1, keepdims=True)
    out = np.einsum("bhqk,bkhd->bqhd", p, v)
    return out, lse


@pytest.mark.parametrize("causal", [False, True])
def test_attention_matches_numpy_oracle(rng, causal):
    q, k, v = (rng.normal(size=(2, 10, 3, 8)).astype(np.float32) for _ in range(3))
    out, lse = attention_with_lse(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), is_causal=causal)
    ref_out, ref_lse = _np_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), ref_out, atol=1e-5)
    np.testing.assert_allclose(np.asarray(lse), ref_lse, atol=1e-5)


def test_attention_cross_lengths(rng):
    q = rng.normal(size=(1, 4, 2, 8)).astype(np.float32)
    k = rng.normal(size=(1, 12, 2, 8)).astype(np.float32)
    v = rng.normal(size=(1, 12, 2, 8)).astype(np.float32)
    out, lse = attention_with_lse(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    assert out.shape == (1, 4, 2, 8) and lse.shape == (1, 2, 4)


def test_key_padding_mask(rng):
    q, k, v = (jnp.asarray(rng.normal(size=(1, 6, 2, 4)), jnp.float32) for _ in range(3))
    mask = jnp.array([[False, False, False, True, True, True]])
    out_masked, _ = attention_with_lse(q, k, v, key_padding_mask=mask)
    out_trunc, _ = attention_with_lse(q, k[:, :3], v[:, :3])
    np.testing.assert_allclose(np.asarray(out_masked), np.asarray(out_trunc), atol=1e-5)


def test_mha_module_shapes_and_params(rng):
    mha = MultiheadAttention(embed_dim=32, num_heads=4, subln=True)
    x = jnp.asarray(rng.normal(size=(2, 9, 32)), jnp.float32)
    params = mha.init(jax.random.PRNGKey(0), x, x, x)
    out = mha.apply(params, x, x, x)
    assert out.shape == (2, 9, 32)
    names = set(params["params"].keys())
    assert {"q_proj", "k_proj", "v_proj", "out_proj", "inner_attn_ln"} <= names


def test_mha_causal_blocks_future(rng):
    mha = MultiheadAttention(embed_dim=16, num_heads=2)
    x = jnp.asarray(rng.normal(size=(1, 8, 16)), jnp.float32)
    params = mha.init(jax.random.PRNGKey(0), x, x, x)
    out1 = mha.apply(params, x, x, x, is_causal=True)
    x2 = x.at[:, -1].set(0.0)  # changing the last token...
    out2 = mha.apply(params, x2, x2, x2, is_causal=True)
    # ...must not change any earlier output position
    np.testing.assert_allclose(np.asarray(out1[:, :-1]), np.asarray(out2[:, :-1]), atol=1e-5)
