"""Suffix-padding correctness: padded+masked forward == unpadded forward.

The property the reference never guarantees (its live path drops the pad
mask, SURVEY §2.7) and that bucketed collation makes load-bearing here: for
every component in the slide path, padding a batch to a larger bucket and
passing the mask must reproduce the unpadded result exactly.
"""

import jax
import jax.numpy as jnp
import numpy as np

from gigapath_tpu.ops.dilated_attention import dilated_attention
from gigapath_tpu.models import slide_encoder as slide_lib
from gigapath_tpu.models.classification_head import ClassificationHead


def test_dilated_attention_valid_len_matches_unpadded(rng):
    B, L, H, D = 2, 24, 4, 8
    pad_to = 32
    q = jnp.asarray(rng.normal(size=(B, pad_to, H, D)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, pad_to, H, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, pad_to, H, D)), jnp.float32)

    out_ref = dilated_attention(
        q[:, :L], k[:, :L], v[:, :L], [8, 16], [1, 2]
    )
    out_masked = dilated_attention(
        q, k, v, [8, 16], [1, 2], valid_len=jnp.asarray([L, L])
    )
    np.testing.assert_allclose(
        np.asarray(out_ref), np.asarray(out_masked[:, :L]), atol=1e-5
    )


def test_dilated_attention_ragged_batch(rng):
    """Different valid lengths per row: each row matches its own unpadded run."""
    B, pad_to, H, D = 2, 32, 4, 8
    lens = [20, 28]
    q = jnp.asarray(rng.normal(size=(B, pad_to, H, D)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, pad_to, H, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, pad_to, H, D)), jnp.float32)
    out = dilated_attention(
        q, k, v, [8, 16], [1, 2], valid_len=jnp.asarray(lens)
    )
    for b, n in enumerate(lens):
        ref = dilated_attention(
            q[b : b + 1, :n], k[b : b + 1, :n], v[b : b + 1, :n], [8, 16], [1, 2]
        )
        np.testing.assert_allclose(
            np.asarray(ref[0]), np.asarray(out[b, :n]), atol=1e-5
        )


def test_slide_encoder_pad_mask_matches_unpadded(rng):
    """LongNetViT: bucketed padding + mask == exact-length forward (the
    finding that motivated this file: without the mask, logits change with
    the bucket size)."""
    model = slide_lib.create_model("", "gigapath_slide_enc_tiny", in_chans=16)[0]
    n, pad_to = 21, 32
    x_full = np.asarray(rng.normal(size=(1, pad_to, 16)), np.float32)
    c_full = np.asarray(rng.uniform(0, 25000, (1, pad_to, 2)), np.float32)
    x_pad, c_pad = x_full.copy(), c_full.copy()
    x_pad[:, n:] = 0.0
    c_pad[:, n:] = 0.0
    mask = np.zeros((1, pad_to), bool)
    mask[:, :n] = True

    params = model.init(
        jax.random.PRNGKey(0), jnp.asarray(x_full), jnp.asarray(c_full)
    )["params"]
    ref = model.apply(
        {"params": params}, jnp.asarray(x_full[:, :n]), jnp.asarray(c_full[:, :n])
    )
    masked = model.apply(
        {"params": params},
        jnp.asarray(x_pad),
        jnp.asarray(c_pad),
        pad_mask=jnp.asarray(mask),
    )
    np.testing.assert_allclose(
        np.asarray(ref[0]), np.asarray(masked[0]), atol=2e-4
    )


def test_slide_encoder_global_pool_excludes_pads(rng):
    model = slide_lib.create_model(
        "", "gigapath_slide_enc_tiny", in_chans=16, global_pool=True
    )[0]
    n, pad_to = 19, 32
    x = np.asarray(rng.normal(size=(1, pad_to, 16)), np.float32)
    c = np.asarray(rng.uniform(0, 25000, (1, pad_to, 2)), np.float32)
    x[:, n:] = 0.0
    mask = np.zeros((1, pad_to), bool)
    mask[:, :n] = True
    params = model.init(jax.random.PRNGKey(0), jnp.asarray(x), jnp.asarray(c))["params"]
    ref = model.apply(
        {"params": params}, jnp.asarray(x[:, :n]), jnp.asarray(c[:, :n])
    )
    masked = model.apply(
        {"params": params}, jnp.asarray(x), jnp.asarray(c), pad_mask=jnp.asarray(mask)
    )
    np.testing.assert_allclose(np.asarray(ref[0]), np.asarray(masked[0]), atol=2e-4)


def test_classification_head_logits_invariant_to_bucket(rng):
    """End-to-end: same slide, two bucket sizes -> identical logits."""
    model = ClassificationHead(
        input_dim=16,
        latent_dim=32,
        feat_layer="1",
        n_classes=3,
        model_arch="gigapath_slide_enc_tiny",
    )
    n = 21
    x = np.asarray(rng.normal(size=(1, n, 16)), np.float32)
    c = np.asarray(rng.uniform(0, 25000, (1, n, 2)), np.float32)
    params = model.init(jax.random.PRNGKey(0), jnp.asarray(x), jnp.asarray(c))["params"]

    logits_by_bucket = []
    for pad_to in (32, 64):
        xp = np.zeros((1, pad_to, 16), np.float32)
        cp = np.zeros((1, pad_to, 2), np.float32)
        xp[:, :n], cp[:, :n] = x, c
        mask = np.zeros((1, pad_to), bool)
        mask[:, :n] = True
        logits_by_bucket.append(
            np.asarray(
                model.apply(
                    {"params": params},
                    jnp.asarray(xp),
                    jnp.asarray(cp),
                    pad_mask=jnp.asarray(mask),
                )
            )
        )
    np.testing.assert_allclose(logits_by_bucket[0], logits_by_bucket[1], atol=2e-4)
