"""Mirrors the reference's runnable self-check (finetune/metrics.py:103-128)."""

import numpy as np

from gigapath_tpu.finetune.metrics import (
    calculate_metrics_with_task_cfg,
    calculate_multiclass_or_binary_metrics,
    calculate_multilabel_metrics,
)

PROBS = np.array(
    [
        [0.7, 0.2, 0.1],
        [0.4, 0.3, 0.3],
        [0.1, 0.8, 0.1],
        [0.2, 0.3, 0.5],
        [0.4, 0.4, 0.2],
        [0.1, 0.2, 0.7],
    ]
)
LABELS = np.eye(3)[[0, 0, 1, 1, 2, 2]]
LABEL_DICT = {"A": 0, "B": 1, "C": 2}


def test_multiclass_metrics_keys_and_ranges():
    res = calculate_multiclass_or_binary_metrics(PROBS, LABELS, LABEL_DICT)
    assert "macro_auroc" in res and "macro_auprc" in res
    assert {"A_auroc", "B_auroc", "C_auroc"} <= set(res)
    assert res["acc"] == 4 / 6
    for v in res.values():
        assert 0.0 <= v <= 1.0


def test_multilabel_metrics():
    res = calculate_multilabel_metrics(PROBS, LABELS, LABEL_DICT)
    assert "micro_auroc" in res and "macro_auroc" in res
    assert "A_auprc" in res


def test_task_cfg_dispatch_with_qwk():
    probs = np.eye(6)[[0, 5, 2, 3, 2, 2, 1, 1, 4]]
    labels = np.eye(6)[[0, 2, 1, 1, 4, 5, 2, 3, 2]]
    cfg = {
        "setting": "multi_class",
        "label_dict": {str(i): i for i in range(6)},
        "add_metrics": ["qwk"],
    }
    res = calculate_metrics_with_task_cfg(probs, labels, cfg)
    assert "qwk" in res
    cfg_ml = {"setting": "multi_label", "label_dict": {str(i): i for i in range(6)}}
    res_ml = calculate_metrics_with_task_cfg(probs, labels, cfg_ml)
    assert "micro_auroc" in res_ml
