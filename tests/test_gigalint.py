"""gigalint wiring: the tree stays clean, and the pass itself works.

Two contracts, both from ISSUE/acceptance:

1. ``python -m tools.gigalint gigapath_tpu scripts`` (and the wider
   gigapath_tpu+scripts+tests scan that lint.sh runs) exits 0 on this
   tree — every finding fixed or explicitly waived with a reason.
2. The seeded-violation fixture tree under tools/gigalint/selftest/
   makes the pass exit NONZERO with every rule class (GL001–GL005)
   firing at least once, while the negative controls stay clean.

These run in the default tier, so every ``pytest -q`` is also a lint run.
"""

import json
import os
import subprocess
import sys

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURE = "tools/gigalint/selftest/fixture"

sys.path.insert(0, REPO_ROOT)

from tools.gigalint.cli import run_lint  # noqa: E402


def test_acceptance_scan_is_clean():
    """The ISSUE acceptance command: gigapath_tpu + scripts, waivers on."""
    result = run_lint(["gigapath_tpu", "scripts"], root=REPO_ROOT)
    assert result.errors == []
    assert result.findings == [], "\n".join(f.text() for f in result.findings)
    assert result.exit_code == 0


def test_full_scan_with_tests_is_clean():
    """The lint.sh scan: tests/ included, so GL005 (pytest hygiene) and
    the test-file-induced trace roots are enforced too."""
    result = run_lint(["gigapath_tpu", "scripts", "tests"], root=REPO_ROOT)
    assert result.errors == []
    assert result.findings == [], "\n".join(f.text() for f in result.findings)
    # the waiver file is in active use — every entry must earn its keep
    assert result.waived, "expected the documented waivers to be exercised"


def test_fixture_tree_fires_every_rule_class():
    result = run_lint([FIXTURE], root=REPO_ROOT, waiver_file=None)
    assert result.exit_code != 0
    fired = {f.rule for f in result.findings}
    expected = {"GL001", "GL002", "GL003", "GL004", "GL005", "GL006",
                "GL007", "GL008", "GL009", "GL010", "GL011", "GL012",
                "GL013", "GL014", "GL015", "GL016", "GL017", "GL022",
                "GL023"}
    assert fired >= expected, (
        f"missing rule classes: {sorted(expected - fired)}"
    )


def test_fixture_negative_controls_stay_clean():
    result = run_lint([FIXTURE], root=REPO_ROOT, waiver_file=None)
    for f in result.findings:
        assert "negative_control" not in f.symbol, f.text()
        assert "test_fixture_fast_without_features" not in f.symbol, f.text()
        # GL017's function-name sanction: the fixture's snapshot_flags
        # twin reads a dispatch flag and must stay clean
        assert not (f.rule == "GL017" and "snapshot_flags" in f.symbol), (
            f.text()
        )


def test_fixture_specific_findings():
    """Each seeded violation is found at its seeded location."""
    result = run_lint([FIXTURE], root=REPO_ROOT, waiver_file=None)
    got = {(f.rule, f.path.rsplit("/", 1)[-1], f.symbol) for f in result.findings}
    expected = {
        ("GL001", "kernels.py", "env_helper"),       # direct read, reachable
        ("GL001", "kernels.py", "kernel_dispatch"),  # helper call + direct
        ("GL002", "kernels.py", "leaky"),
        # compound condition: an is-None guard must not shadow the leak
        ("GL002", "kernels.py", "leaky_compound"),
        ("GL003", "net.py", "uncovered_proj"),
        ("GL003", "net.py", "<anonymous>"),
        ("GL004", "net.py", "make_net"),
        ("GL004", "net.py", "eval"),
        ("GL004", "net.py", "except"),
        ("GL005", "test_hygiene.py", "test_fixture_flag_parity_slow"),
        ("GL005", "test_hygiene.py", "test_fixture_seq_parallel_slow"),
        ("GL006", "driver.py", "noisy_train_loop"),
        ("GL006", "driver.py", "<module>"),
        ("GL007", "driver.py", "undocumented_flag_knob"),
        # unfenced wall-clock deltas around device work (direct jit call
        # and a watchdog.wrap-bound handle)
        ("GL008", "timing.py", "timed_no_fence"),
        ("GL008", "timing.py", "timed_wrapped_no_fence"),
        # span(fence=None) is explicitly unfenced: no fence credit
        ("GL008", "timing.py", "timed_span_fence_none"),
        # seq-parallel collective without a _SEQ_COLLECTIVES entry (the
        # sanctioned twin in sanctioned_ring.py is the negative control)
        ("GL009", "ring.py", "ring_exchange_unregistered"),
        # open-ended jax.profiler pair outside obs/spans.py (the
        # fixture's own obs/spans.py twin is the negative control)
        ("GL010", "profiler.py", "trace_by_hand"),
        # signal.signal outside obs/flight.py (the fixture's own
        # obs/flight.py twin is the negative control)
        ("GL011", "handlers.py", "install_cleanup_handler"),
        # hand-rolled latency aggregation (time deltas -> list.append ->
        # sort) outside obs/ (the fixture's own obs/metrics.py twin is
        # the negative control, as are timing-without-sort and
        # sort-without-timing)
        ("GL012", "latency.py", "aggregate_latency_by_hand"),
        ("GL012", "latency.py", "aggregate_latency_sorted_copy"),
        # attribute-owned list (sorted(self._walls)) — the serving-stats
        # shape must not slip past a bare-Name-only sorted() check
        ("GL012", "latency.py", "LatencyStat.aggregate"),
        # unbounded hand-rolled inter-thread channels (the fixture's
        # own dist/boundary.py + serve/queue.py twins are the sanctioned
        # negative controls, rolling.py the no-threading deque control)
        ("GL013", "channels.py", "unbounded_queue_channel"),
        ("GL013", "channels.py", "unbounded_deque_channel"),
        # chunk reassembly inside a streaming-sanctioned module (the
        # fixture twins ops/streaming_prefill.py by path suffix; the
        # *dense_fallback* oracle stays a negative control)
        ("GL014", "streaming_prefill.py", "reassemble_chunks"),
        ("GL014", "streaming_prefill.py", "stack_chunks_for_readout"),
        # maxsize=-1 is Python's explicitly-INFINITE queue, not a bound
        ("GL013", "channels.py", "unbounded_queue_negative_maxsize"),
        # raw socket plumbing outside the sanctioned dist/transport.py
        # (whose fixture twin is the negative control for the
        # connection-primitive check)...
        ("GL015", "sockets.py", "open_raw_socket"),
        ("GL015", "sockets.py", "dial_without_deadline"),
        ("GL015", "sockets.py", "serve_with_socketserver"),
        ("GL015", "sockets.py", "recv_without_timeout"),
        # a 3-positional select.select(r, w, x) blocks forever: no
        # deadline credit (only selectors' select(timeout) or stdlib's
        # 4th positional count)
        ("GL015", "sockets.py", "select_without_timeout"),
        # ...and the deadline discipline fires EVEN inside the
        # sanctioned transport module
        ("GL015", "transport.py", "recv_without_deadline"),
        # raw low-precision casts outside the sanctioned quant/ package
        # (the fixture's own quant/qtensor.py twin is the negative
        # control, as are the bf16/uint8/int32 casts in lowprec.py)
        ("GL016", "lowprec.py", "cast_weights_by_hand"),
        ("GL016", "lowprec.py", "pack_activations"),
        ("GL016", "lowprec.py", "fp8_by_hand"),
        ("GL016", "lowprec.py", "stage_buffer"),
        # kernel-dispatch flag reads outside snapshot_flags / the plan
        # package (the fixture's own plan/resolve.py twin is the
        # path-segment negative control; dispatch.py::snapshot_flags is
        # the function-name negative control; host flags + dynamic
        # names stay out of scope)
        ("GL017", "dispatch.py", "read_variant_flag_by_hand"),
        ("GL017", "dispatch.py", "block_override_by_hand"),
        ("GL017", "dispatch.py", "helper_env_flag_read"),
        ("GL017", "dispatch.py", "subscript_read"),
        # untraced spans in dist/ library code (the fixture twins
        # dist/worker.py; the traced span and the manual ctx.add_span
        # call are the negative controls): a missing trace= kwarg and
        # an explicit trace=None both fall out of the fleet timeline
        ("GL022", "worker.py", "untraced_encode_span"),
        ("GL022", "worker.py", "untraced_none_span"),
        # hand-rolled running-moment accumulators (Welford triple by
        # hand) outside obs/ (the sketch-routed path, the mean-only
        # loop and the count-plus-product loop are the negative
        # controls)
        ("GL023", "moments.py", "running_moments_by_hand"),
        ("GL023", "moments.py", "MomentTracker.observe"),
    }
    assert expected <= got, f"missing: {sorted(expected - got)}"


def test_cli_json_output_and_exit_codes():
    proc = subprocess.run(
        [sys.executable, "-m", "tools.gigalint", "--json", "--no-waivers",
         FIXTURE],
        capture_output=True, text=True, cwd=REPO_ROOT,
    )
    assert proc.returncode == 1, proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["findings"], "JSON output must carry the findings"
    assert all(
        {"rule", "path", "lineno", "symbol", "message"} <= set(f)
        for f in payload["findings"]
    )

    proc = subprocess.run(
        [sys.executable, "-m", "tools.gigalint", "gigapath_tpu", "scripts"],
        capture_output=True, text=True, cwd=REPO_ROOT,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_waiver_without_reason_is_an_error(tmp_path):
    waivers = tmp_path / "WAIVERS"
    waivers.write_text("GL004 somewhere.py\n")  # no '-- reason'
    result = run_lint([FIXTURE], root=REPO_ROOT, waiver_file=str(waivers))
    assert any("justification" in e for e in result.errors)
    assert result.exit_code == 2


def test_waiver_suppresses_with_reason(tmp_path):
    waivers = tmp_path / "WAIVERS"
    waivers.write_text(
        "GL004 tools/gigalint/selftest/fixture/models/net.py::eval"
        " -- fixture: seeded violation\n"
    )
    result = run_lint([FIXTURE], root=REPO_ROOT, waiver_file=str(waivers))
    assert not any(
        f.rule == "GL004" and f.symbol == "eval" for f in result.findings
    )
    assert any(
        f.rule == "GL004" and f.symbol == "eval"
        and f.waived_by == "fixture: seeded violation"
        for f in result.waived
    )


def test_inline_waiver(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(
        "def f(path):\n"
        "    return eval(path)  # gigalint: waive GL004 -- test inline\n"
    )
    result = run_lint([str(bad)], root=REPO_ROOT, waiver_file=None)
    assert result.findings == []
    assert any(f.waived_by == "inline: test inline" for f in result.waived)


def test_lint_sh_exists_and_points_at_the_tool():
    script = os.path.join(REPO_ROOT, "scripts", "lint.sh")
    assert os.path.exists(script)
    with open(script) as f:
        body = f.read()
    assert "tools.gigalint" in body
    assert os.access(script, os.X_OK), "lint.sh must be executable"


# ---------------------------------------------------------------------------
# stale waivers: matched-but-unused entries are ERRORS, not warnings
# ---------------------------------------------------------------------------

STALE_FIXTURE_WAIVERS = "tools/gigalint/selftest/stale_waivers/WAIVERS"


def test_stale_waiver_fixture_classifies_all_three_ways():
    """The committed fixture seeds one USED entry, one STALE entry
    (glob in scope, suppresses nothing -> error, exit 2), and one
    OUT-OF-SCOPE entry (warning only)."""
    result = run_lint(
        ["tools/gigalint/selftest/fixture/models/timing.py"],
        root=REPO_ROOT, waiver_file=STALE_FIXTURE_WAIVERS,
        strict_waivers=True,
    )
    assert result.exit_code == 2
    stale = [e for e in result.errors if "stale waiver" in e]
    assert len(stale) == 1, result.errors
    assert "no_such_symbol_seeded_stale" in stale[0]
    # it names the waiver file line so the purge is one click away
    assert STALE_FIXTURE_WAIVERS + ":" in stale[0]
    assert result.unused_waivers == [
        "GL008 gigapath_tpu/models/no_such_file_seeded.py"
    ]
    # the used entry raised no complaint of either kind
    assert not any("USED" in e for e in result.errors)


def test_stale_waiver_silent_under_select():
    """With --select a waiver's rule may simply not have run — no stale
    errors, no unused warnings (pruning on partial evidence would break
    the full run)."""
    result = run_lint(
        ["tools/gigalint/selftest/fixture/models/timing.py"],
        root=REPO_ROOT, waiver_file=STALE_FIXTURE_WAIVERS,
        select=["GL004"], strict_waivers=True,
    )
    assert not any("stale waiver" in e for e in result.errors)
    assert result.unused_waivers == []


def test_repo_waiver_file_has_no_stale_entries():
    """The purge contract: lint.sh's canonical strict scan must never
    carry a matched-but-dead suppression at HEAD. (Strict only holds on
    the FULL scope — reachability rules draw evidence from tests/.)"""
    result = run_lint(["gigapath_tpu", "scripts", "tests"], root=REPO_ROOT,
                      strict_waivers=True)
    stale = [e for e in result.errors if "stale waiver" in e]
    assert stale == [], "\n".join(stale)
    assert result.exit_code == 0


# ---------------------------------------------------------------------------
# --jobs: parallel parsing is invisible in the output
# ---------------------------------------------------------------------------

def _fingerprint(result):
    return (
        [(f.rule, f.path, f.lineno, f.symbol, f.message)
         for f in result.findings],
        [(f.rule, f.path, f.lineno, f.symbol, f.waived_by)
         for f in result.waived],
        result.errors,
        result.scanned,
        result.unused_waivers,
    )


def test_jobs_output_is_deterministic():
    """Findings, waivers, errors and their ORDER are byte-identical at
    any parallelism — Executor.map pins module order to discovery
    order, and everything downstream sorts."""
    serial = run_lint([FIXTURE], root=REPO_ROOT, waiver_file=None, jobs=1)
    for jobs in (2, 8):
        parallel = run_lint(
            [FIXTURE], root=REPO_ROOT, waiver_file=None, jobs=jobs,
        )
        assert _fingerprint(parallel) == _fingerprint(serial), (
            f"jobs={jobs} changed the output"
        )
    assert serial.findings, "fixture scan should find the seeded violations"


def test_jobs_parse_errors_keep_position(tmp_path):
    """A syntactically broken file reports the same error at the same
    list position regardless of which worker hit it."""
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "a_ok.py").write_text("x = 1\n")
    (pkg / "broken.py").write_text("def f(:\n")
    (pkg / "z_ok.py").write_text("y = 2\n")
    results = [
        run_lint(["pkg"], root=str(tmp_path), waiver_file=None, jobs=jobs)
        for jobs in (1, 4)
    ]
    for r in results:
        assert r.scanned == 2
        assert len(r.errors) == 1 and "syntax error" in r.errors[0]
    assert results[0].errors == results[1].errors
