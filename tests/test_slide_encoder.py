"""LongNetViT slide encoder, factory, checkpoint conversion, classification head."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gigapath_tpu.models import slide_encoder
from gigapath_tpu.models.classification_head import (
    ClassificationHead,
    frozen_param_labels,
    get_model,
    parse_feat_layer,
)
from gigapath_tpu.models.slide_encoder import LongNetViT, get_optimal_segment_length
from gigapath_tpu.utils.registry import MODEL_REGISTRY


SMALL = dict(
    embed_dim=192, depth=1, slide_ngrids=100, segment_length=[512, 1024, 2048],
    dilated_ratio="[1, 2, 4]", dropout=0.0, drop_path_rate=0.0,
)


def _small_vit(**kw):
    return LongNetViT(in_chans=64, **{**SMALL, **kw})


def test_optimal_segment_length_matches_reference_formula():
    # reference slide_encoder.py:137-154: linspace in log2 from 1024 to
    # int(log2((max_wsi/tile)^2)), 5 points, floored to int
    assert get_optimal_segment_length(262144, 256) == [1024, 5792, 32768, 185363, 1048576]
    # run_panda.sh MAX_WSI_SIZE=250000 -> top segment 2^19
    sched = get_optimal_segment_length(250000, 256)
    assert sched[0] == 1024 and sched[-1] == 524288 and len(sched) == 5
    assert sched == sorted(sched)


def test_registry_archs_present():
    for arch in ["gigapath_slide_enc12l768d", "gigapath_slide_enc24l1024d", "gigapath_slide_enc12l1536d"]:
        assert arch in MODEL_REGISTRY


def test_forward_shapes(rng):
    model = _small_vit()
    x = jnp.asarray(rng.normal(size=(2, 17, 64)), jnp.float32)
    coords = jnp.asarray(rng.integers(0, 100 * 256, size=(2, 17, 2)), jnp.float32)
    params = model.init(jax.random.PRNGKey(0), x, coords)["params"]
    outs = model.apply({"params": params}, x, coords)
    assert len(outs) == 1 and outs[0].shape == (2, 192)
    outs_all = model.apply({"params": params}, x, coords, all_layer_embed=True)
    assert len(outs_all) == 2  # embedding + 1 layer
    assert all(o.shape == (2, 192) for o in outs_all)


def test_global_pool_differs_from_cls(rng):
    x = jnp.asarray(rng.normal(size=(1, 9, 64)), jnp.float32)
    coords = jnp.zeros((1, 9, 2), jnp.float32)
    m1 = _small_vit(global_pool=False)
    params = m1.init(jax.random.PRNGKey(0), x, coords)["params"]
    m2 = _small_vit(global_pool=True)
    o1 = m1.apply({"params": params}, x, coords)[0]
    o2 = m2.apply({"params": params}, x, coords)[0]
    assert not np.allclose(np.asarray(o1), np.asarray(o2))


def test_create_model_random_init(capsys):
    model, params = slide_encoder.create_model(
        "", "gigapath_slide_enc12l768d", in_chans=1536,
        segment_length=[512], dilated_ratio="[1]", slide_ngrids=100,
    )
    n_params = sum(np.prod(p.shape) for p in jax.tree_util.tree_leaves(params))
    # ~86M params for the 12l/768d flagship (SURVEY §6)
    assert 80e6 < n_params < 95e6


def test_torch_checkpoint_roundtrip(tmp_path, rng):
    """Save a reference-shaped torch state dict, convert, verify merge."""
    import torch

    model = _small_vit()
    x = jnp.asarray(rng.normal(size=(1, 5, 64)), jnp.float32)
    coords = jnp.zeros((1, 5, 2), jnp.float32)
    params = model.init(jax.random.PRNGKey(0), x, coords)["params"]

    D, F = 192, 192  # LongNet_test-ish dims for depth-1 192d
    state = {
        "cls_token": torch.randn(1, 1, D),
        "pos_embed": torch.zeros(1, 100 * 100 + 1, D),  # must be skipped
        "patch_embed.proj.weight": torch.randn(D, 64),
        "patch_embed.proj.bias": torch.randn(D),
        "norm.weight": torch.ones(D),
        "norm.bias": torch.zeros(D),
        "encoder.layer_norm.weight": torch.ones(D),
        "encoder.layer_norm.bias": torch.zeros(D),
    }
    for proj in ["q_proj", "k_proj", "v_proj", "out_proj"]:
        state[f"encoder.layers.0.self_attn.{proj}.weight"] = torch.randn(D, D)
        state[f"encoder.layers.0.self_attn.{proj}.bias"] = torch.randn(D)
    state["encoder.layers.0.self_attn.inner_attn_ln.weight"] = torch.ones(D)
    state["encoder.layers.0.self_attn.inner_attn_ln.bias"] = torch.zeros(D)
    for ln in ["self_attn_layer_norm", "final_layer_norm"]:
        state[f"encoder.layers.0.{ln}.weight"] = torch.ones(D)
        state[f"encoder.layers.0.{ln}.bias"] = torch.zeros(D)
    state["encoder.layers.0.ffn.fc1.weight"] = torch.randn(768, D)
    state["encoder.layers.0.ffn.fc1.bias"] = torch.randn(768)
    state["encoder.layers.0.ffn.fc2.weight"] = torch.randn(D, 768)
    state["encoder.layers.0.ffn.fc2.bias"] = torch.randn(D)
    state["encoder.layers.0.ffn.ffn_layernorm.weight"] = torch.ones(768)
    state["encoder.layers.0.ffn.ffn_layernorm.bias"] = torch.zeros(768)

    from gigapath_tpu.utils.torch_convert import convert_state_dict, merge_into_params

    converted = convert_state_dict(state)  # handles layers.0 -> layers_0
    new_params, missing, unexpected = merge_into_params(params, converted)
    # ffn dims differ in the tiny test model (192 vs 768) -> those are reported
    assert not any("pos_embed" in u for u in unexpected)
    # the loaded q_proj kernel is the transpose of the torch weight
    w = state["encoder.layers.0.self_attn.q_proj.weight"].numpy()
    np.testing.assert_allclose(
        np.asarray(new_params["encoder"]["layers_0"]["self_attn"]["q_proj"]["kernel"]), w.T
    )


def test_parse_feat_layer():
    assert parse_feat_layer("5-11") == [5, 11]
    assert parse_feat_layer("11") == [11]


def test_classification_head_forward(rng):
    head = ClassificationHead(
        input_dim=64, latent_dim=192, feat_layer="0-1", n_classes=3,
        model_arch="gigapath_slide_enc12l768d",
        slide_kwargs=dict(
            embed_dim=192, depth=1, slide_ngrids=50,
            segment_length=[256], dilated_ratio="[1]", dropout=0.0, drop_path_rate=0.0,
        ),
    )
    # model_arch registry fn overrides embed_dim/depth via kwargs... use direct module
    x = jnp.asarray(rng.normal(size=(1, 7, 64)), jnp.float32)
    coords = jnp.zeros((1, 7, 2), jnp.float32)
    params = head.init(jax.random.PRNGKey(0), x, coords)["params"]
    logits = head.apply({"params": params}, x, coords)
    assert logits.shape == (1, 3)
    labels = frozen_param_labels(params)
    flat = jax.tree_util.tree_leaves(labels)
    assert "frozen" in flat and "trainable" in flat
