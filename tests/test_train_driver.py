"""train_gigapath driver: rename -> tile -> extract (cached) -> labels -> train."""

import os

import numpy as np
import pytest
from PIL import Image

from gigapath_tpu.models.tile_encoder import VisionTransformer, init_params
from gigapath_tpu.train_gigapath import (
    create_dummy_labels,
    extract_features,
    main as train_main,
    rename_slide_files,
)


def _slides(tmp_path, n=2, seed=0):
    rng = np.random.default_rng(seed)
    data_dir = tmp_path / "slides"
    data_dir.mkdir()
    for i in range(n):
        arr = np.full((256, 256, 3), 245, np.uint8)
        arr[64:192, 96:224] = rng.integers(30, 120, (128, 128, 3))
        # a query-string suffix exercises the rename step
        name = f"slide_{i}.png?download=1" if i == 0 else f"slide_{i}.png"
        Image.fromarray(arr).save(data_dir / f"slide_{i}.png")
        if i == 0:
            os.rename(data_dir / "slide_0.png", data_dir / name)
    return str(data_dir)


def test_rename_and_full_journey(tmp_path, rng):
    data_dir = _slides(tmp_path)
    files = rename_slide_files(data_dir)
    assert all("?" not in f for f in files) and len(files) == 2

    enc = VisionTransformer(
        img_size=32, patch_size=16, embed_dim=16, depth=1, num_heads=4, mlp_ratio=2.0
    )
    params = init_params(enc)
    out_dir = str(tmp_path / "out")
    result = train_main(
        data_dir,
        out_dir,
        tile_encoder=enc,
        tile_params=params,
        num_epochs=2,
        model_arch="gigapath_slide_enc_tiny",
        latent_dim=32,
        feat_layer="1",
        freeze_pretrained=False,
    )
    assert len(result["loss_history"]) == 2
    assert np.isfinite(result["loss_history"]).all()
    assert os.path.exists(os.path.join(out_dir, "labels.csv"))

    # second extract run hits the cache (skip-if-processed)
    feature_dir = os.path.join(out_dir, "features")
    paths = extract_features(files, feature_dir, tile_encoder=enc, tile_params=params)
    assert len(paths) == 2


def test_create_dummy_labels_distribution(tmp_path):
    feature_dir = tmp_path / "features"
    feature_dir.mkdir()
    for i in range(6):
        (feature_dir / f"s{i}_features").mkdir()
    out = create_dummy_labels(str(feature_dir), str(tmp_path / "labels.csv"), 3)
    import pandas as pd

    df = pd.read_csv(out)
    assert len(df) == 6 and set(df["label"]) <= {0, 1, 2}
