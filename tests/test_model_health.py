"""Model-health observability contracts (ISSUE 19).

Four surfaces, each pinned both ways:

- **numerics** (`gigapath_tpu.obs.numerics`): flag-off the step lowers
  to byte-identical HLO (the summaries are never traced); flag-on the
  summaries are shape-static, so steps 2..N reuse step 1's executable —
  zero retraces. NaN propagation is explicit: a non-finite layer owns
  the worst-absmax verdict.
- **EmbeddingSketch** (`gigapath_tpu.obs.drift`): Chan's merge is
  associative and equivalent to single-pass folding; save/load is
  bit-exact (restart-resume keeps producing the same sketch); a
  tampered artifact is refused loudly (`CorruptDriftArtifact`).
- **DriftSentinel + `embedding_drift` detector**: a chaos-shifted
  serve fires EXACTLY ONE anomaly (with flight dump) per regime —
  transition-edged, terminal status never fires; a clean serve fires
  none.
- **anytime peeks** (`StreamingEncoderSession.peek`): provisional
  embeddings converge to the finalized one as the frontier advances,
  and the full-frontier peek is BIT-exact vs `finalize()` (identical
  op sequence) — the anchor of the `serve.stream_confidence` surface.
"""

import json
import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gigapath_tpu.obs.drift import (
    CorruptDriftArtifact,
    DriftSentinel,
    EmbeddingSketch,
    cosine,
    drift_scores,
)
from gigapath_tpu.obs.numerics import (
    NumericsMonitor,
    group_summaries,
    numerics_enabled,
    numerics_layers,
    numerics_scalars,
    split_numerics,
)
from gigapath_tpu.obs.runlog import RunLog
from gigapath_tpu.obs.telemetry import step_scalars


def _read_events(path):
    with open(path, encoding="utf-8") as fh:
        return [json.loads(line) for line in fh if line.strip()]


# ---------------------------------------------------------------------------
# numerics: in-graph summaries behind GIGAPATH_NUMERICS
# ---------------------------------------------------------------------------


def _toy_params():
    return {
        "encoder": {"w": jnp.ones((4, 4)) * 0.5, "b": jnp.zeros((4,))},
        "head": {"w": jnp.ones((4, 2)) * 0.25},
    }


def _make_step(numerics_on: bool):
    """The finetune gate, verbatim shape: a Python bool decides at
    trace time whether the summary reductions exist in the program."""

    def loss_fn(params, x):
        h = x @ params["encoder"]["w"] + params["encoder"]["b"]
        return jnp.sum(jnp.square(h @ params["head"]["w"]))

    @jax.jit
    def step(params, x):
        loss, grads = jax.value_and_grad(loss_fn)(params, x)
        tel = step_scalars(loss=loss, grads=grads)
        if numerics_on:
            tel.update(numerics_scalars(grads=grads))
        return loss, tel

    return step


class TestNumericsFlag:
    def test_default_off(self, monkeypatch):
        monkeypatch.delenv("GIGAPATH_NUMERICS", raising=False)
        assert not numerics_enabled()
        monkeypatch.setenv("GIGAPATH_NUMERICS", "1")
        assert numerics_enabled()
        monkeypatch.setenv("GIGAPATH_NUMERICS", "0")
        assert not numerics_enabled()

    def test_flag_off_hlo_byte_identical(self):
        """numerics_on=False must lower to the same PROGRAM as a build
        without the branch at all. Only op source locations may differ
        (`metadata={...}` spans) — the ops, layouts and schedule must
        be byte-equal."""

        def loss_fn(params, x):
            h = x @ params["encoder"]["w"] + params["encoder"]["b"]
            return jnp.sum(jnp.square(h @ params["head"]["w"]))

        # the pre-ISSUE-19 step body, no numerics branch anywhere
        @jax.jit
        def step(params, x):
            loss, grads = jax.value_and_grad(loss_fn)(params, x)
            tel = step_scalars(loss=loss, grads=grads)
            return loss, tel

        args = (_toy_params(), jnp.ones((3, 4)))

        def hlo(fn):
            text = fn.lower(*args).compile().as_text()
            return re.sub(r", metadata={[^}]*}", "", text)

        reference = hlo(step)
        assert hlo(_make_step(False)) == reference
        # sanity: flag-on is a different program (the reductions exist)
        assert hlo(_make_step(True)) != reference

    def test_flag_on_zero_retraces(self):
        """The summaries are shape-static functions of the pytree, so
        repeated steps share one executable."""
        step = _make_step(True)
        params = _toy_params()
        for i in range(3):
            _, tel = step(params, jnp.ones((3, 4)) * (i + 1))
        assert step._cache_size() == 1
        # every scalar left the step as a 0-d device array, float()-able
        # only at the sync point the caller picks
        synced = {k: float(v) for k, v in tel.items()}
        assert any(k.startswith("num.grad.") for k in synced)

    def test_group_summaries_values_and_nan(self):
        tree = {
            "clean": {"w": jnp.asarray([3.0, -4.0])},
            "broken": {"w": jnp.asarray([1.0, jnp.nan, 2.0, 8.0])},
        }
        out = {k: float(v) for k, v in
               group_summaries(tree, prefix="num.grad").items()}
        assert out["num.grad.clean.finite_frac"] == 1.0
        assert out["num.grad.clean.absmax"] == 4.0
        assert out["num.grad.clean.rms"] == pytest.approx(
            np.sqrt((9 + 16) / 2))
        assert out["num.grad.broken.finite_frac"] == 0.75
        # absmax must PROPAGATE the NaN, not mask it behind the 8.0
        assert np.isnan(out["num.grad.broken.absmax"])

    def test_split_monitor_and_nan_wins_worst(self, tmp_path):
        tel = {"loss": 1.5, "grad_norm": 0.3,
               "num.grad.a.finite_frac": 1.0, "num.grad.a.absmax": 3.5,
               "num.grad.a.rms": 0.7,
               "num.grad.b.finite_frac": 0.5,
               "num.grad.b.absmax": float("nan"), "num.grad.b.rms": 0.1}
        rest, num = split_numerics(tel)
        assert set(rest) == {"loss", "grad_norm"}
        assert len(num) == 6
        assert numerics_layers(num)["grad.b"]["finite_frac"] == 0.5

        log = RunLog(str(tmp_path / "run.jsonl"), driver="t", echo=False)
        mon = NumericsMonitor(log, name="t")
        record = mon.emit(40, num)
        log.close()
        assert mon.emitted == 1
        assert record["worst_finite_frac"] == 0.5
        # max() is order-dependent with NaN; the monitor must not be
        assert np.isnan(record["worst_absmax"])
        assert record["layers"]["grad.a"]["absmax"] == 3.5
        assert mon.emit(41, {"loss": 1.0}) is None  # nothing numeric


# ---------------------------------------------------------------------------
# EmbeddingSketch: merge algebra + artifact discipline
# ---------------------------------------------------------------------------


def _filled(rng, dim=6, n=20, loc=0.0):
    sk = EmbeddingSketch(dim)
    for _ in range(n):
        sk.update(rng.normal(loc, 1.0, dim))
    return sk


class TestEmbeddingSketch:
    def test_merge_associative_and_matches_single_pass(self):
        rng = np.random.default_rng(3)
        data = rng.normal(size=(30, 6))
        a, b, c = EmbeddingSketch(6), EmbeddingSketch(6), EmbeddingSketch(6)
        whole = EmbeddingSketch(6)
        for i, row in enumerate(data):
            (a, b, c)[i % 3].update(row)
            whole.update(row)
        left = a.merge(b).merge(c)
        right = a.merge(b.merge(c))
        for merged in (left, right):
            assert merged.count == whole.count == 30
            np.testing.assert_allclose(merged.mean, whole.mean,
                                       rtol=0, atol=1e-9)
            np.testing.assert_allclose(merged.m2, whole.m2,
                                       rtol=1e-9, atol=1e-9)
            np.testing.assert_array_equal(merged.hist, whole.hist)
        np.testing.assert_allclose(left.mean, right.mean, atol=1e-12)
        # merge is non-mutating: a is untouched
        assert a.count == 10

    def test_merge_geometry_mismatch_refused(self):
        with pytest.raises(ValueError):
            EmbeddingSketch(4).merge(EmbeddingSketch(5))

    def test_save_load_restart_resume_bit_exact(self, tmp_path):
        rng = np.random.default_rng(7)
        sk = _filled(rng, n=12)
        path = str(tmp_path / "baseline")
        sk.save(path)
        resumed = EmbeddingSketch.load(path)
        assert resumed.count == sk.count
        np.testing.assert_array_equal(resumed.mean, sk.mean)
        np.testing.assert_array_equal(resumed.m2, sk.m2)
        np.testing.assert_array_equal(resumed.hist, sk.hist)
        # restart-resume: both continue over the same stream and stay
        # BIT-exact — a restarted serving process loses nothing
        tail = np.random.default_rng(11).normal(size=(8, 6))
        for row in tail:
            sk.update(row)
            resumed.update(row)
        np.testing.assert_array_equal(resumed.mean, sk.mean)
        np.testing.assert_array_equal(resumed.m2, sk.m2)
        np.testing.assert_array_equal(resumed.hist, sk.hist)
        # overwrite-in-place goes through the same atomic swap
        resumed.save(path)
        assert EmbeddingSketch.load(path).count == 20

    def test_corrupt_artifact_refused(self, tmp_path):
        rng = np.random.default_rng(5)
        path = str(tmp_path / "baseline")
        _filled(rng).save(path)
        npz = path + "/sketch.npz"
        with open(npz, "rb") as fh:
            blob = bytearray(fh.read())
        blob[len(blob) // 2] ^= 0xFF
        with open(npz, "wb") as fh:
            fh.write(blob)
        with pytest.raises(CorruptDriftArtifact):
            EmbeddingSketch.load(path)

    def test_missing_manifest_refused(self, tmp_path):
        with pytest.raises(CorruptDriftArtifact):
            EmbeddingSketch.load(str(tmp_path / "nowhere"))

    def test_quantile_and_tail(self):
        sk = EmbeddingSketch(1, bins=8, hi=8.0)
        for v in (1.0, 2.0, 3.0, 4.0):
            sk.update(np.asarray([v]))
        assert sk.quantile(0.99) >= 4.0
        assert sk.mass_above(100.0) == 0.0
        assert sk.mass_above(0.0) == 1.0


# ---------------------------------------------------------------------------
# DriftSentinel + embedding_drift detector: both ways
# ---------------------------------------------------------------------------


class TestDriftSentinel:
    def _run(self, tmp_path, shift):
        from gigapath_tpu.obs.anomaly import (
            AnomalyConfig,
            attach_anomaly_engine,
        )

        rng = np.random.default_rng(2)
        baseline = _filled(rng, n=24)
        log = RunLog(str(tmp_path / f"run{shift}.jsonl"), driver="t",
                     echo=False)
        attach_anomaly_engine(log, config=AnomalyConfig(capture_budget=0))
        sentinel = DriftSentinel(baseline, log, every=2, threshold=3.0,
                                 min_count=2, name="t.drift")
        for _ in range(8):
            sentinel.observe(rng.normal(shift, 1.0, 6))
        sentinel.emit_status(reason="final")
        log.close()
        events = _read_events(str(tmp_path / f"run{shift}.jsonl"))
        anomalies = [e for e in events if e.get("kind") == "anomaly"
                     and e.get("detector") == "embedding_drift"]
        return sentinel, events, anomalies

    def test_forced_drift_fires_exactly_one_with_flight(self, tmp_path):
        sentinel, events, anomalies = self._run(tmp_path, shift=7.0)
        assert sentinel.alarming
        assert sentinel.scores["mean_shift"] > 3.0
        # transition-edged: 4 scoring points past the threshold, ONE
        # anomaly; the terminal final=True status never fires
        assert len(anomalies) == 1
        assert anomalies[0]["flight"]
        assert anomalies[0]["name"] == "t.drift"
        finals = [e for e in events if e.get("kind") == "drift"
                  and e.get("final")]
        assert len(finals) == 1 and finals[0]["alarming"]

    def test_clean_serve_fires_none(self, tmp_path):
        sentinel, events, anomalies = self._run(tmp_path, shift=0.0)
        assert not sentinel.alarming
        assert anomalies == []
        # the terminal status still lands, so reports render drift
        # health on clean runs too
        assert any(e.get("kind") == "drift" and e.get("final")
                   for e in events)

    def test_scores_shape(self):
        rng = np.random.default_rng(9)
        base, cur = _filled(rng), _filled(rng, loc=4.0)
        scores = drift_scores(cur, base)
        assert set(scores) == {"mean_shift", "cosine_dist", "tail_mass"}
        assert scores["mean_shift"] > 1.0
        assert 0.0 <= scores["cosine_dist"] <= 2.0
        assert drift_scores(base, base)["cosine_dist"] == 0.0

    def test_min_count_gates_scoring(self, tmp_path):
        rng = np.random.default_rng(4)
        log = RunLog(str(tmp_path / "run.jsonl"), driver="t", echo=False)
        sentinel = DriftSentinel(_filled(rng), log, every=1, threshold=0.1,
                                 min_count=6, name="t.drift")
        for _ in range(5):
            sentinel.observe(rng.normal(9.0, 1.0, 6))
        assert sentinel.scores is None and not sentinel.alarming
        sentinel.observe(rng.normal(9.0, 1.0, 6))
        log.close()
        assert sentinel.alarming


# ---------------------------------------------------------------------------
# anytime peeks: provisional-vs-final convergence
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def tiny_model():
    """One param init shared by the whole class — init dominates the
    per-test cost, and every test here builds its own fresh session on
    top of the same frozen (inner, params) pair."""
    from gigapath_tpu.models.classification_head import get_model
    from gigapath_tpu.utils.registry import create_model_from_registry

    _, params = get_model(
        input_dim=16, latent_dim=32, feat_layer="1", n_classes=2,
        model_arch="gigapath_slide_enc_tiny", dtype=None,
    )
    inner = create_model_from_registry(
        "gigapath_slide_enc_tiny", in_chans=16, global_pool=False,
        dtype=None,
    )
    return inner, params


def _fresh_session(tiny_model, n_tiles, chunk_tiles, runlog=None):
    from gigapath_tpu.models.streaming_encoder import StreamingEncoderSession

    inner, params = tiny_model
    return StreamingEncoderSession(
        inner, params["slide_encoder"], n_tiles,
        chunk_tiles=chunk_tiles, runlog=runlog,
    )


class TestAnytimePeek:
    def test_peek_before_any_fold_raises(self, tiny_model):
        session = _fresh_session(tiny_model, 16, 8)
        with pytest.raises(RuntimeError):
            session.peek()

    @pytest.mark.slow
    def test_convergence_monotone_and_full_frontier_bit_exact(
            self, tiny_model):
        # compiles one executable per frontier shape (4 peeks +
        # finalize) — the faster sibling in the default tier is
        # test_submitter_confidence_surface, which drives the same
        # peek path through the serve wiring with fewer frontiers
        n_tiles, chunk_tiles = 32, 8
        session = _fresh_session(tiny_model, n_tiles, chunk_tiles)
        rng = np.random.default_rng(0)
        feats = rng.normal(size=(n_tiles, 16)).astype(np.float32)
        coords = rng.uniform(0, 1000, (n_tiles, 2)).astype(np.float32)

        peeks = []
        for idx in range(4):
            a, b = idx * chunk_tiles, (idx + 1) * chunk_tiles
            session.feed(idx, feats[a:b], coords[a:b])
            peeks.append(np.asarray(session.peek()[-1],
                                    np.float32).reshape(-1))
            assert np.isfinite(session.lse_spread())
        final = np.asarray(session.finalize()[-1], np.float32).reshape(-1)

        confidences = [cosine(p, final) for p in peeks]
        # provisional answers converge toward the final one as the
        # frontier advances: the last pre-complete peek is at least as
        # confident as the first (the serve.stream_confidence claim)
        assert confidences[-2] >= confidences[0] - 1e-6
        assert confidences[-2] > 0.5
        # ... and the full-frontier peek IS the final answer, bit-exact
        # (identical op sequence — the convergence anchor)
        np.testing.assert_array_equal(peeks[-1], final)
        assert confidences[-1] == pytest.approx(1.0, abs=1e-6)

    def test_submitter_confidence_surface(self, tiny_model, tmp_path):
        """The serve wiring end-to-end: peeks emit `stream_peek`
        events, finalize scores provisional-vs-final into
        `stream_result` + the `serve.stream_confidence` histogram."""
        from gigapath_tpu.obs.metrics import MetricsRegistry
        from gigapath_tpu.serve.streaming import StreamingSubmitter

        inner, params = tiny_model
        run_path = str(tmp_path / "run.jsonl")
        log = RunLog(run_path, driver="t", echo=False)
        registry = MetricsRegistry(runlog=log, interval_s=0)
        sub = StreamingSubmitter(inner, params["slide_encoder"],
                                 chunk_tiles=8, runlog=log, peek_every=1,
                                 metrics=registry)
        rng = np.random.default_rng(1)
        n_tiles = 24
        feats = rng.normal(size=(n_tiles, 16)).astype(np.float32)
        coords = rng.uniform(0, 1000, (n_tiles, 2)).astype(np.float32)
        session = sub.open("s0", n_tiles)
        for idx in range(3):
            session.feed(idx, feats[idx * 8:(idx + 1) * 8],
                         coords[idx * 8:(idx + 1) * 8])
        out = session.result()
        assert out["last_layer_embed"].shape[-1] == 32
        registry.flush(reason="final")
        log.close()

        events = _read_events(run_path)
        peeks = [e for e in events if e.get("kind") == "stream_peek"]
        # cadence 1, 3 chunks: peeks at frontiers 1 and 2 (a peek at
        # the full frontier would duplicate the result)
        assert [e["frontier"] for e in peeks] == [1, 2]
        assert peeks[0]["cos_prev"] is None
        assert isinstance(peeks[1]["cos_prev"], float)
        results = [e for e in events if e.get("kind") == "stream_result"]
        assert len(results) == 1 and results[0]["peeks"] == 2
        assert 0.0 < results[0]["confidence_last"] <= 1.0
        assert (results[0]["confidence_last"]
                >= results[0]["confidence_first"] - 1e-6)
        snap = [e for e in events if e.get("kind") == "metrics"][-1]
        hist = snap["histograms"]["serve.stream_confidence"]
        assert hist["count"] == 2
