"""Tile encoder (flax ViT-G/14) tests.

Oracle strategy: the reference consumes the tile encoder through timm
(``gigapath/pipeline.py:126-128``); timm is not in this environment, so the
oracle is a hand-written torch-functional forward implementing the timm
DINOv2 block math (conv patch embed, packed qkv, LayerScale, SwiGLU) from a
timm-named state dict. The converter + flax model must reproduce it exactly.

The golden-tile parity test (reference ``demo/3_load_tile_encoder.py:28-34``,
atol 1e-2 vs ``images/prov_normal_000_1.pt``) additionally needs the real
1.13 B-param pretrained checkpoint, which is not available in the zero-egress
environment — it runs whenever ``GIGAPATH_TILE_ENCODER_CKPT`` points at one.
"""

import math
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch
import torch.nn.functional as F

from gigapath_tpu.models.tile_encoder import (
    VisionTransformer,
    convert_timm_state_dict,
    count_params,
    create_tile_encoder,
    init_params,
    interpolate_pos_embed,
)
from gigapath_tpu.utils.torch_convert import merge_into_params

TINY = dict(
    img_size=32, patch_size=16, embed_dim=32, depth=2, num_heads=4,
    mlp_ratio=4.0, swiglu=True, init_values=1e-5,
)


def make_timm_state_dict(cfg, seed=0):
    """Random timm-named ViT state dict for the given config."""
    g = torch.Generator().manual_seed(seed)
    D, depth = cfg["embed_dim"], cfg["depth"]
    p = cfg["patch_size"]
    n_tok = (cfg["img_size"] // p) ** 2 + 1
    hidden = int(D * cfg["mlp_ratio"])
    fc2_in = hidden // 2 if cfg["swiglu"] else hidden

    def t(*shape):
        return torch.randn(*shape, generator=g) * 0.05

    sd = {
        "cls_token": t(1, 1, D),
        "pos_embed": t(1, n_tok, D),
        "patch_embed.proj.weight": t(D, 3, p, p),
        "patch_embed.proj.bias": t(D),
        "norm.weight": 1.0 + t(D),
        "norm.bias": t(D),
    }
    for i in range(depth):
        b = f"blocks.{i}."
        sd.update(
            {
                b + "norm1.weight": 1.0 + t(D),
                b + "norm1.bias": t(D),
                b + "attn.qkv.weight": t(3 * D, D),
                b + "attn.qkv.bias": t(3 * D),
                b + "attn.proj.weight": t(D, D),
                b + "attn.proj.bias": t(D),
                b + "ls1.gamma": t(D),
                b + "norm2.weight": 1.0 + t(D),
                b + "norm2.bias": t(D),
                b + "mlp.fc1.weight": t(hidden, D),
                b + "mlp.fc1.bias": t(hidden),
                b + "mlp.fc2.weight": t(D, fc2_in),
                b + "mlp.fc2.bias": t(D),
                b + "ls2.gamma": t(D),
            }
        )
    return sd


def torch_vit_forward(sd, x, cfg):
    """timm DINOv2 ViT forward in plain torch functional ops (the oracle)."""
    D, H = cfg["embed_dim"], cfg["num_heads"]
    depth, p = cfg["depth"], cfg["patch_size"]
    hd = D // H
    eps = 1e-6
    B = x.shape[0]

    x = F.conv2d(x, sd["patch_embed.proj.weight"], sd["patch_embed.proj.bias"], stride=p)
    x = x.flatten(2).transpose(1, 2)  # [B, N, D]
    cls = sd["cls_token"].expand(B, -1, -1)
    x = torch.cat([cls, x], dim=1) + sd["pos_embed"]
    N = x.shape[1]

    for i in range(depth):
        b = f"blocks.{i}."
        h = F.layer_norm(x, (D,), sd[b + "norm1.weight"], sd[b + "norm1.bias"], eps)
        qkv = F.linear(h, sd[b + "attn.qkv.weight"], sd[b + "attn.qkv.bias"])
        qkv = qkv.reshape(B, N, 3, H, hd).permute(2, 0, 3, 1, 4)
        q, k, v = qkv.unbind(0)
        attn = (q * hd**-0.5) @ k.transpose(-2, -1)
        attn = attn.softmax(dim=-1)
        h = (attn @ v).transpose(1, 2).reshape(B, N, D)
        h = F.linear(h, sd[b + "attn.proj.weight"], sd[b + "attn.proj.bias"])
        x = x + h * sd[b + "ls1.gamma"]

        h = F.layer_norm(x, (D,), sd[b + "norm2.weight"], sd[b + "norm2.bias"], eps)
        h = F.linear(h, sd[b + "mlp.fc1.weight"], sd[b + "mlp.fc1.bias"])
        if cfg["swiglu"]:
            h1, h2 = h.chunk(2, dim=-1)
            h = F.silu(h1) * h2
        else:
            h = F.gelu(h)
        h = F.linear(h, sd[b + "mlp.fc2.weight"], sd[b + "mlp.fc2.bias"])
        x = x + h * sd[b + "ls2.gamma"]

    x = F.layer_norm(x, (D,), sd["norm.weight"], sd["norm.bias"], eps)
    return x[:, 0]


@pytest.mark.parametrize("swiglu", [True, False])
def test_forward_matches_torch_oracle(swiglu):
    cfg = dict(TINY, swiglu=swiglu)
    sd = make_timm_state_dict(cfg)
    model = VisionTransformer(**cfg)
    params = init_params(model)
    converted = convert_timm_state_dict(sd)
    params, missing, unexpected = merge_into_params(params, converted)
    assert missing == [], missing
    assert unexpected == [], unexpected

    rng = np.random.default_rng(0)
    img = rng.normal(size=(2, 32, 32, 3)).astype(np.float32)
    out = model.apply({"params": params}, jnp.asarray(img))
    ref = torch_vit_forward(sd, torch.from_numpy(img).permute(0, 3, 1, 2), cfg)
    np.testing.assert_allclose(np.asarray(out), ref.numpy(), atol=1e-5, rtol=1e-5)


def test_forward_features_tokens():
    model = VisionTransformer(**TINY)
    params = init_params(model)
    x = jnp.zeros((1, 32, 32, 3))
    tokens = model.apply({"params": params}, x, method=model.forward_features)
    assert tokens.shape == (1, 1 + 4, 32)


def test_gigapath_param_count():
    """The printed reference count (gigapath/pipeline.py:129): 1.13 B."""
    from gigapath_tpu.models.tile_encoder import gigapath_tile_enc

    n = count_params(gigapath_tile_enc())
    assert n == 1_134_953_984, n


def test_pos_embed_interpolation_shapes_and_identity():
    D = 8
    table = np.random.default_rng(0).normal(size=(1, 1 + 16, D)).astype(np.float32)
    same = interpolate_pos_embed(table, 4)
    np.testing.assert_array_equal(same, table)
    up = interpolate_pos_embed(table, 8)
    assert up.shape == (1, 1 + 64, D)
    # cls row untouched
    np.testing.assert_array_equal(up[:, 0], table[:, 0])


def test_create_tile_encoder_checkpoint_roundtrip(tmp_path):
    cfg = TINY
    sd = make_timm_state_dict(cfg, seed=3)
    path = tmp_path / "tile_encoder.pth"
    torch.save(sd, path)
    model, params = create_tile_encoder(str(path), "vit_tile_enc_test")
    rng = np.random.default_rng(1)
    img = rng.normal(size=(1, 32, 32, 3)).astype(np.float32)
    out = model.apply({"params": params}, jnp.asarray(img))
    ref = torch_vit_forward(sd, torch.from_numpy(img).permute(0, 3, 1, 2), cfg)
    np.testing.assert_allclose(np.asarray(out), ref.numpy(), atol=1e-5, rtol=1e-5)


def test_pos_embed_resize_on_grid_mismatch(tmp_path):
    """A checkpoint trained at a different grid loads via interpolation."""
    cfg = dict(TINY, img_size=64)  # grid 4 target
    sd = make_timm_state_dict(TINY)  # grid 2 checkpoint
    converted = convert_timm_state_dict(sd, target_grid=4)
    model = VisionTransformer(**cfg)
    params = init_params(model)
    params, missing, unexpected = merge_into_params(params, converted)
    assert missing == [] and unexpected == []


def test_vendored_timm_key_schema_maps_bijectively():
    """The full-size ViT-G timm key schema (vendored fixture, names+shapes
    only — regenerate with scripts/gen_timm_fixture.py) maps one-to-one onto
    the flax param tree with exact shapes, covering every parameter.

    This is the strongest converter evidence available in a zero-egress
    environment; the weights-level golden check is ``test_golden_tile_parity``
    below (README "Verifying tile-encoder parity").
    """
    import json

    fixture = os.path.join(os.path.dirname(__file__), "fixtures", "timm_vitg_keys.json")
    with open(fixture) as f:
        schema = {k: tuple(v) for k, v in json.load(f).items()}

    # param count of the schema == the derived timm model size
    assert sum(int(np.prod(s)) for s in schema.values()) == 1_134_953_984

    from gigapath_tpu.models.tile_encoder import gigapath_tile_enc

    model = gigapath_tile_enc()
    x = jax.ShapeDtypeStruct((1, 224, 224, 3), jnp.float32)
    tree = jax.eval_shape(model.init, jax.random.PRNGKey(0), x)["params"]
    flat = {
        tuple(getattr(p, "key", str(p)) for p in path): leaf.shape
        for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]
    }

    # stream keys one at a time (full zero tensors would cost ~4.5 GB)
    converted: dict = {}
    for key, shape in schema.items():
        (path, arr), = convert_timm_state_dict(
            {key: np.zeros(shape, np.float32)}
        ).items()
        assert path not in converted, f"{key} collides at {path}"
        converted[path] = arr.shape

    assert set(converted) == set(flat), (
        sorted(set(flat) - set(converted))[:5],
        sorted(set(converted) - set(flat))[:5],
    )
    for path, shape in converted.items():
        assert tuple(flat[path]) == tuple(shape), (path, flat[path], shape)


GOLDEN_CKPT = os.environ.get("GIGAPATH_TILE_ENCODER_CKPT", "")
GOLDEN_PNG = "/root/reference/images/prov_normal_000_1.png"
GOLDEN_PT = "/root/reference/images/prov_normal_000_1.pt"


@pytest.mark.skipif(
    not (GOLDEN_CKPT and os.path.exists(GOLDEN_CKPT) and os.path.exists(GOLDEN_PT)),
    reason="pretrained ViT-G checkpoint not available (zero-egress environment)",
)
def test_golden_tile_parity():
    """Reference demo/3_load_tile_encoder.py:28-34: atol 1e-2 vs golden."""
    from PIL import Image

    from gigapath_tpu.data.transforms import preprocess_tile

    model, params = create_tile_encoder(GOLDEN_CKPT, "gigapath_tile_enc")
    img = preprocess_tile(Image.open(GOLDEN_PNG))
    out = model.apply({"params": params}, jnp.asarray(img)[None])
    golden = torch.load(GOLDEN_PT, map_location="cpu", weights_only=True).numpy()
    np.testing.assert_allclose(np.asarray(out), golden, atol=1e-2)
