"""Dilated attention vs independent numpy oracle + vanilla equivalence.

The reference's own statement of correctness is its `LongNet_Vanilla_*`
configs (dilated ratio [1], segment 10^7 => must equal full attention); we
test that plus a general multi-branch oracle the reference never had.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gigapath_tpu.ops.attention import attention_with_lse
from gigapath_tpu.ops.dilated_attention import (
    DilatedAttention,
    dense_to_sparse,
    dilated_attention,
    sparse_to_dense,
)


def _np_softmax_attn(q, k, v):
    D = q.shape[-1]
    logits = q @ k.T / np.sqrt(D)
    m = logits.max(-1, keepdims=True)
    e = np.exp(logits - m)
    p = e / e.sum(-1, keepdims=True)
    lse = np.log(e.sum(-1)) + m[:, 0]
    return p @ v, lse


def _np_dilated_oracle(q, k, v, branches):
    """Per-position/per-head oracle: each branch restricts attention to the
    dilated subset of its segment; branches fuse by softmax over lse."""
    B, N, H, D = q.shape
    outs = np.zeros((len(branches), B, N, H, D))
    lses = np.full((len(branches), B, N, H), -1e8)
    for bi, (sl, r) in enumerate(branches):
        g = min(sl, N)
        heads_per_group = -(-H // r)
        for b in range(B):
            for s0 in range(0, N, g):
                for h in range(H):
                    phase = h // heads_per_group
                    pos = np.arange(s0 + phase, min(s0 + g, N), r)
                    if len(pos) == 0:
                        continue
                    o, lse = _np_softmax_attn(q[b, pos, h], k[b, pos, h], v[b, pos, h])
                    outs[bi, b, pos, h] = o
                    lses[bi, b, pos, h] = lse
    w = np.exp(lses - lses.max(0))
    w = w / w.sum(0)
    return (outs * w[..., None]).sum(0)


def test_dense_sparse_roundtrip(rng):
    x = jnp.asarray(rng.normal(size=(3, 8, 4, 5)), jnp.float32)
    s = dense_to_sparse(x, 2)
    assert s.shape == (3, 4, 4, 5)
    lse = jnp.zeros((3, 4, 4))
    d, lse_d = sparse_to_dense(s, lse, 2, 8)
    # every selected position must round-trip exactly
    s2 = dense_to_sparse(d, 2)
    np.testing.assert_allclose(np.asarray(s2), np.asarray(s))
    # uncovered positions have NEG_INF lse
    assert (np.asarray(lse_d) == -1e8).sum() == 3 * 4 * 4


@pytest.mark.parametrize("sl", [64, 1_000_000])
def test_single_branch_ratio1_equals_vanilla(rng, sl):
    q, k, v = (jnp.asarray(rng.normal(size=(2, 32, 4, 8)), jnp.float32) for _ in range(3))
    out = dilated_attention(q, k, v, [sl], [1])
    ref, _ = attention_with_lse(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_segmented_ratio1_is_block_diagonal(rng):
    q, k, v = (jnp.asarray(rng.normal(size=(1, 32, 2, 8)), jnp.float32) for _ in range(3))
    out = dilated_attention(q, k, v, [8], [1])
    for s in range(0, 32, 8):
        ref, _ = attention_with_lse(q[:, s : s + 8], k[:, s : s + 8], v[:, s : s + 8])
        np.testing.assert_allclose(np.asarray(out[:, s : s + 8]), np.asarray(ref), atol=1e-5)


@pytest.mark.parametrize(
    "branches,N,H",
    [
        ([(8, 2)], 16, 4),
        ([(4, 1), (8, 2)], 16, 4),
        ([(4, 1), (8, 2), (16, 4)], 32, 8),
        ([(8, 4)], 16, 2),  # more phases than heads-per-group edge
        ([(6, 2)], 13, 4),  # non-power-of-two, padding paths
    ],
)
def test_multibranch_matches_oracle(rng, branches, N, H):
    q, k, v = (rng.normal(size=(2, N, H, 4)).astype(np.float32) for _ in range(3))
    out = dilated_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
        [sl for sl, _ in branches], [r for _, r in branches],
    )
    ref = _np_dilated_oracle(q, k, v, branches)
    np.testing.assert_allclose(np.asarray(out), ref, atol=2e-5, rtol=1e-4)


def test_causal_single_branch(rng):
    q, k, v = (jnp.asarray(rng.normal(size=(1, 16, 2, 4)), jnp.float32) for _ in range(3))
    out = dilated_attention(q, k, v, [16], [1], is_causal=True)
    ref, _ = attention_with_lse(q, k, v, is_causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_module_gigapath_schedule(rng):
    """Flagship 5-branch schedule on a short sequence (all sl >= N)."""
    mod = DilatedAttention(
        embed_dim=32,
        num_heads=4,
        segment_length=(1024, 2048, 4096, 8192, 16384),
        dilated_ratio=(1, 2, 4, 8, 16),
    )
    x = jnp.asarray(rng.normal(size=(1, 100, 32)), jnp.float32)
    params = mod.init(jax.random.PRNGKey(0), x, x, x)
    out = mod.apply(params, x, x, x)
    assert out.shape == (1, 100, 32)
    assert np.isfinite(np.asarray(out)).all()


def test_gradients_flow(rng):
    q, k, v = (jnp.asarray(rng.normal(size=(1, 16, 2, 4)), jnp.float32) for _ in range(3))

    def loss(q):
        return dilated_attention(q, k, v, [4, 8], [1, 2]).sum()

    g = jax.grad(loss)(q)
    assert np.isfinite(np.asarray(g)).all()
    assert np.abs(np.asarray(g)).sum() > 0


def test_seq_parallel_matches_single_device(rng):
    """shard_map over a 4-way seq axis == single-device dilated attention."""
    from jax.sharding import Mesh, PartitionSpec as P
    from jax import shard_map

    devs = np.array(jax.devices()[:4])
    mesh = Mesh(devs, ("seq",))
    N, H, D = 32, 4, 8
    q, k, v = (jnp.asarray(rng.normal(size=(1, N, H, D)), jnp.float32) for _ in range(3))
    sls, drs = [4, 16, 32], [1, 2, 4]  # 16 and 32 exceed the 8-token local shard

    ref = dilated_attention(q, k, v, sls, drs)

    fn = shard_map(
        lambda q, k, v: dilated_attention(
            q, k, v, sls, drs, seq_axis_name="seq", seq_axis_size=4
        ),
        mesh=mesh,
        in_specs=(P(None, "seq"), P(None, "seq"), P(None, "seq")),
        out_specs=P(None, "seq"),
    )
    out = fn(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=1e-4)


def test_seq_parallel_causal_matches_single_device(rng):
    """Causal shard_map SP == single-device causal dilated attention.

    Covers reference ``gather_kv``'s causal branch (dilated_attention.py:64-68)
    with the corrected semantics (own-rank keys kept, causal across rank
    blocks) — see PARITY.md for the deviation note.
    """
    from jax.sharding import Mesh, PartitionSpec as P
    from jax import shard_map

    devs = np.array(jax.devices()[:4])
    mesh = Mesh(devs, ("seq",))
    N, H, D = 32, 4, 8
    q, k, v = (jnp.asarray(rng.normal(size=(1, N, H, D)), jnp.float32) for _ in range(3))
    sls, drs = [4, 16, 32], [1, 2, 4]  # 16 and 32 exceed the 8-token local shard

    ref = dilated_attention(q, k, v, sls, drs, is_causal=True)

    fn = shard_map(
        lambda q, k, v: dilated_attention(
            q, k, v, sls, drs, is_causal=True, seq_axis_name="seq", seq_axis_size=4
        ),
        mesh=mesh,
        in_specs=(P(None, "seq"), P(None, "seq"), P(None, "seq")),
        out_specs=P(None, "seq"),
    )
    out = fn(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=1e-4)


class TestOffsetDecode:
    """Incremental decoding (offset > 0, Lq != Lk) == rows of the full
    causal forward — the contract of reference ``gathering``/``scattering``
    with ``offset`` (dilated_attention.py:78-82,113)."""

    SLS, DRS = [4, 16], [1, 2]

    def test_stepwise_matches_full(self, rng):
        N, H, D = 24, 4, 8  # N > 16: caches longer than the largest segment
        q, k, v = (jnp.asarray(rng.normal(size=(2, N, H, D)), jnp.float32) for _ in range(3))
        full = dilated_attention(q, k, v, self.SLS, self.DRS, is_causal=True)
        for t in [0, 1, 3, 4, 7, 15, 16, 17, 23]:
            step = dilated_attention(
                q[:, t : t + 1], k[:, : t + 1], v[:, : t + 1],
                self.SLS, self.DRS, is_causal=True, offset=t,
            )
            np.testing.assert_allclose(
                np.asarray(step[:, 0]), np.asarray(full[:, t]),
                atol=2e-5, rtol=1e-4, err_msg=f"step {t}",
            )

    def test_chunked_matches_full(self, rng):
        """Multi-token chunks, including chunks crossing segment boundaries."""
        N, H, D = 24, 4, 8
        q, k, v = (jnp.asarray(rng.normal(size=(1, N, H, D)), jnp.float32) for _ in range(3))
        full = dilated_attention(q, k, v, self.SLS, self.DRS, is_causal=True)
        for t0, t1 in [(0, 3), (3, 9), (9, 24)]:  # (3,9) crosses the sl=4 boundary
            chunk = dilated_attention(
                q[:, t0:t1], k[:, :t1], v[:, :t1],
                self.SLS, self.DRS, is_causal=True, offset=t0,
            )
            np.testing.assert_allclose(
                np.asarray(chunk), np.asarray(full[:, t0:t1]),
                atol=2e-5, rtol=1e-4, err_msg=f"chunk [{t0}, {t1})",
            )

    def test_bad_cache_length_raises(self, rng):
        q, k, v = (jnp.asarray(rng.normal(size=(1, 8, 2, 4)), jnp.float32) for _ in range(3))
        with pytest.raises(ValueError, match="offset"):
            dilated_attention(
                q[:, :1], k, v, self.SLS, self.DRS, is_causal=True, offset=3
            )


def test_longnet_decoder_incremental_matches_full(rng):
    """LongNetDecoder eager stepwise generation == full-sequence forward
    (reference ``LongNetDecoder``, model/LongNet.py:30-45)."""
    from gigapath_tpu.architecture.config import DecoderConfig
    from gigapath_tpu.models.longnet import LongNetDecoder

    cfg = DecoderConfig(
        decoder_embed_dim=32,
        decoder_attention_heads=4,
        decoder_ffn_embed_dim=64,
        decoder_layers=2,
        vocab_size=50,
        dropout=0.0,
        drop_path_rate=0.0,
        segment_length=[4, 16],
        dilated_ratio=[1, 2],
        flash_attention=True,
    )
    dec = LongNetDecoder(cfg)
    T = 9
    tokens = jnp.asarray(rng.integers(0, 50, (2, T)), jnp.int32)
    variables = dec.init(jax.random.PRNGKey(0), tokens, decode=True)
    params, cache = variables["params"], variables["cache"]
    full = dec.apply({"params": params}, tokens)["decoder_out"]

    step_outs = []
    for t in range(T):
        out, mods = dec.apply(
            {"params": params, "cache": cache},
            tokens[:, t : t + 1],
            decode=True,
            mutable=["cache"],
        )
        cache = mods["cache"]
        step_outs.append(out["decoder_out"][:, 0])
    stepped = jnp.stack(step_outs, axis=1)
    np.testing.assert_allclose(np.asarray(full), np.asarray(stepped), atol=2e-4)


class TestBHLDFastPath:
    """Head-major (BHLD) fast path == generic path / numpy oracle.

    On CPU the auto-dispatch in ``dilated_attention`` never takes this path
    (it is TPU-only), so these tests call ``dilated_attention_bhld``
    directly — jnp tier and Pallas tier (interpret mode) both.
    """

    CASES = [
        ([(4, 1), (8, 2), (16, 4)], 32, 8),
        ([(8, 4)], 16, 2),
        ([(6, 2)], 13, 4),
        ([(64, 1), (128, 2), (512, 4)], 523, 12),
    ]

    @pytest.mark.parametrize("branches,N,H", CASES)
    def test_jnp_tier_matches_oracle(self, rng, branches, N, H):
        from gigapath_tpu.ops.dilated_attention import dilated_attention_bhld

        q, k, v = (rng.normal(size=(2, N, H, 4)).astype(np.float32) for _ in range(3))
        out = dilated_attention_bhld(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
            [sl for sl, _ in branches], [r for _, r in branches],
            use_pallas=False,
        )
        ref = _np_dilated_oracle(q, k, v, branches)
        np.testing.assert_allclose(np.asarray(out), ref, atol=2e-5, rtol=1e-4)

    @pytest.mark.parametrize("branches,N,H", CASES[:2])
    def test_pallas_tier_matches_oracle(self, rng, branches, N, H):
        from gigapath_tpu.ops.dilated_attention import dilated_attention_bhld

        q, k, v = (rng.normal(size=(2, N, H, 4)).astype(np.float32) for _ in range(3))
        out = dilated_attention_bhld(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
            [sl for sl, _ in branches], [r for _, r in branches],
            use_pallas=True, interpret=True,
        )
        ref = _np_dilated_oracle(q, k, v, branches)
        np.testing.assert_allclose(np.asarray(out), ref, atol=2e-5, rtol=1e-4)

    def test_valid_len_matches_generic(self, rng):
        from gigapath_tpu.ops.dilated_attention import dilated_attention_bhld

        q, k, v = (jnp.asarray(rng.normal(size=(2, 40, 4, 8)), jnp.float32) for _ in range(3))
        ref = dilated_attention(q, k, v, [8, 16], [1, 2], valid_len=29)
        out = dilated_attention_bhld(q, k, v, [8, 16], [1, 2], valid_len=29, use_pallas=False)
        np.testing.assert_allclose(
            np.asarray(out[:, :29]), np.asarray(ref[:, :29]), atol=2e-5, rtol=1e-4
        )

    def test_traced_valid_len_matches_generic(self, rng):
        """TRACED per-batch valid lengths ride the Pallas tier (SMEM
        counts) — the fine-tune train path's masked batches must not fall
        back to the generic dense-probability tier."""
        from gigapath_tpu.ops.dilated_attention import dilated_attention_bhld

        q, k, v = (jnp.asarray(rng.normal(size=(2, 40, 4, 8)), jnp.float32) for _ in range(3))
        vlen = jnp.asarray([29, 37], jnp.int32)
        ref = dilated_attention(q, k, v, [8, 16], [1, 2], valid_len=vlen)
        out = jax.jit(
            lambda q, k, v, vl: dilated_attention_bhld(
                q, k, v, [8, 16], [1, 2], valid_len=vl,
                use_pallas=True, interpret=True,
            )
        )(q, k, v, vlen)
        for b, n in enumerate([29, 37]):
            np.testing.assert_allclose(
                np.asarray(out[b, :n]), np.asarray(ref[b, :n]),
                atol=2e-5, rtol=1e-4,
            )

    def test_traced_valid_len_gradients(self, rng):
        from gigapath_tpu.ops.dilated_attention import dilated_attention_bhld

        q, k, v = (jnp.asarray(rng.normal(size=(1, 24, 4, 8)), jnp.float32) for _ in range(3))
        vlen = jnp.asarray([17], jnp.int32)

        def loss_p(q):
            o = dilated_attention_bhld(
                q, k, v, [8, 16], [1, 2], valid_len=vlen,
                use_pallas=True, interpret=True,
            )
            return (o[:, :17] ** 2).sum()

        def loss_r(q):
            o = dilated_attention(q, k, v, [8, 16], [1, 2], valid_len=vlen)
            return (o[:, :17] ** 2).sum()

        g1, g2 = jax.grad(loss_p)(q), jax.grad(loss_r)(q)
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), atol=2e-4, rtol=1e-3)

    def test_causal_matches_generic(self, rng):
        from gigapath_tpu.ops.dilated_attention import dilated_attention_bhld

        q, k, v = (jnp.asarray(rng.normal(size=(1, 32, 4, 8)), jnp.float32) for _ in range(3))
        ref = dilated_attention(q, k, v, [8, 32], [1, 2], is_causal=True)
        out = dilated_attention_bhld(q, k, v, [8, 32], [1, 2], is_causal=True, use_pallas=False)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=1e-4)

    def test_gradients_match_generic(self, rng):
        from gigapath_tpu.ops.dilated_attention import dilated_attention_bhld

        q, k, v = (jnp.asarray(rng.normal(size=(1, 24, 4, 8)), jnp.float32) for _ in range(3))

        def loss_bhld(q):
            return dilated_attention_bhld(
                q, k, v, [8, 16], [1, 2], use_pallas=True, interpret=True
            ).sum()

        def loss_ref(q):
            return dilated_attention(q, k, v, [8, 16], [1, 2]).sum()

        g1, g2 = jax.grad(loss_bhld)(q), jax.grad(loss_ref)(q)
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), atol=2e-4, rtol=1e-3)


class TestFusedPhaseMajorPath:
    """Phase-major fused kernels (pallas_dilated.py) == oracle/generic path.

    CPU-only via interpret mode; on TPU these kernels back
    ``dilated_attention_fused``.
    """

    @pytest.mark.parametrize(
        "branches,N,H",
        [
            ([(4, 1), (8, 2), (16, 4)], 32, 8),
            ([(64, 1), (128, 2), (512, 4)], 523, 16),
        ],
    )
    def test_matches_oracle(self, rng, branches, N, H):
        from gigapath_tpu.ops.dilated_attention import dilated_attention_fused

        q, k, v = (rng.normal(size=(2, N, H, 4)).astype(np.float32) for _ in range(3))
        out = dilated_attention_fused(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
            [sl for sl, _ in branches], [r for _, r in branches],
            interpret=True,
        )
        ref = _np_dilated_oracle(q, k, v, branches)
        np.testing.assert_allclose(np.asarray(out), ref, atol=2e-5, rtol=1e-4)

    def test_traced_valid_len_matches_static(self, rng):
        """A TRACED per-batch valid length (collate pad masks) must ride
        the fused kernels' SMEM tables and match the static-int result —
        forward AND gradients (the fine-tune train path depends on it)."""
        from gigapath_tpu.ops.dilated_attention import dilated_attention_fused

        B, N, H, D = 2, 40, 4, 8
        q, k, v = (
            jnp.asarray(rng.normal(size=(B, N, H, D)), jnp.float32)
            for _ in range(3)
        )
        vl = jnp.asarray([29, 33], jnp.int32)

        def run(q, k, v, valid_len):
            return dilated_attention_fused(
                q, k, v, [8, 16], [1, 2], valid_len=valid_len, interpret=True
            )

        out_t = run(q, k, v, vl)
        for b, n in enumerate((29, 33)):
            out_s = dilated_attention_fused(
                q[b : b + 1], k[b : b + 1], v[b : b + 1], [8, 16], [1, 2],
                valid_len=n, interpret=True,
            )
            np.testing.assert_allclose(
                np.asarray(out_t[b, :n]), np.asarray(out_s[0, :n]),
                atol=2e-5, rtol=1e-4,
            )

        def loss_t(q, k, v):
            return (run(q, k, v, vl)[:, :29] ** 2).sum()

        def loss_s(q, k, v):
            return (run(q, k, v, 29)[:, :29] ** 2).sum()

        g_t = jax.grad(loss_t, argnums=(0, 1, 2))(q, k, v)
        g_s = jax.grad(loss_s, argnums=(0, 1, 2))(q, k, v)
        # batch 0 has valid length 29 in both variants: its gradients agree
        for a, b, name in zip(g_t, g_s, "qkv"):
            assert np.abs(np.asarray(a)).sum() > 0, f"d{name} is vacuously zero"
            np.testing.assert_allclose(
                np.asarray(a[0]), np.asarray(b[0]), atol=2e-5, rtol=1e-4,
                err_msg=f"d{name} traced != static on batch 0",
            )

    def test_valid_len_and_causal_match_generic(self, rng):
        from gigapath_tpu.ops.dilated_attention import dilated_attention_fused

        q, k, v = (jnp.asarray(rng.normal(size=(2, 40, 4, 8)), jnp.float32) for _ in range(3))
        ref = dilated_attention(q, k, v, [8, 16], [1, 2], valid_len=29)
        out = dilated_attention_fused(q, k, v, [8, 16], [1, 2], valid_len=29, interpret=True)
        np.testing.assert_allclose(
            np.asarray(out[:, :29]), np.asarray(ref[:, :29]), atol=2e-5, rtol=1e-4
        )
        ref_c = dilated_attention(q, k, v, [8, 32], [1, 2], is_causal=True)
        out_c = dilated_attention_fused(q, k, v, [8, 32], [1, 2], is_causal=True, interpret=True)
        np.testing.assert_allclose(np.asarray(out_c), np.asarray(ref_c), atol=2e-5, rtol=1e-4)

    def test_gradients_match_generic(self, rng):
        from gigapath_tpu.ops.dilated_attention import dilated_attention_fused

        q, k, v = (jnp.asarray(rng.normal(size=(1, 24, 4, 8)), jnp.float32) for _ in range(3))
        for arg in range(3):
            def loss_f(x, arg=arg):
                a = [q, k, v]
                a[arg] = x
                return dilated_attention_fused(*a, [8, 16], [1, 2], interpret=True).sum()

            def loss_r(x, arg=arg):
                a = [q, k, v]
                a[arg] = x
                return dilated_attention(*a, [8, 16], [1, 2]).sum()

            g1, g2 = jax.grad(loss_f)([q, k, v][arg]), jax.grad(loss_r)([q, k, v][arg])
            np.testing.assert_allclose(
                np.asarray(g1), np.asarray(g2), atol=2e-4, rtol=1e-3
            )

    def test_odd_ratio_falls_back(self, rng):
        """A ratio not dividing H routes through the head-major branch."""
        from gigapath_tpu.ops.dilated_attention import dilated_attention_fused

        q, k, v = (jnp.asarray(rng.normal(size=(1, 24, 4, 8)), jnp.float32) for _ in range(3))
        out = dilated_attention_fused(q, k, v, [8, 12], [1, 3], interpret=True)
        ref = dilated_attention(q, k, v, [8, 12], [1, 3])
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=1e-4)


def test_streaming_fusion_matches_stacked(rng):
    """Online-over-branches fusion must be numerically identical to the
    stacked LSE-softmax fusion. (It enables the long-context envelope; its
    accumulator deliberately KEEPS the branch [B,H,L,D] layout — a
    lane-clean [B,L,H,D] accumulator was tried in round 4 and regressed
    256k from 12.7 GB to an OOM, see the comment in the streaming block.)"""
    from gigapath_tpu.ops.dilated_attention import dilated_attention_bhld

    B, L, H, Dh = 1, 512, 4, 16
    q, k, v = (
        jnp.asarray(rng.normal(size=(B, L, H, Dh)), jnp.float32)
        for _ in range(3)
    )
    kwargs = dict(
        segment_lengths=[128, 256, 512], dilated_ratios=[1, 2, 4],
        valid_len=500, interpret=True,
    )
    stacked = dilated_attention_bhld(q, k, v, streaming_fusion=False, **kwargs)
    streamed = dilated_attention_bhld(q, k, v, streaming_fusion=True, **kwargs)
    np.testing.assert_allclose(
        np.asarray(streamed), np.asarray(stacked), atol=2e-6, rtol=1e-5
    )


def test_fused_streaming_matches_stacked(rng):
    """Fused-path online-over-branches fusion == stacked fusion (the
    long-context memory mode on the default kernel path)."""
    from gigapath_tpu.ops.dilated_attention import dilated_attention_fused

    B, L, H, Dh = 1, 64, 4, 8
    q, k, v = (
        jnp.asarray(rng.normal(size=(B, L, H, Dh)), jnp.float32)
        for _ in range(3)
    )
    kwargs = dict(
        segment_lengths=[16, 32, 64], dilated_ratios=[1, 2, 4],
        valid_len=60, interpret=True,
    )
    stacked = dilated_attention_fused(q, k, v, streaming_fusion=False, **kwargs)
    streamed = dilated_attention_fused(q, k, v, streaming_fusion=True, **kwargs)
    np.testing.assert_allclose(
        np.asarray(streamed)[:, :60], np.asarray(stacked)[:, :60],
        atol=2e-6, rtol=1e-5,
    )


@pytest.mark.slow
@pytest.mark.parametrize(
    "L,sl,r,rl",
    [
        (300, 64, 1, 300),      # nk == 1, single head band
        (300, 64, 2, 277),      # nk == 1, phases + ragged tail
        (1280, 1280, 1, 1280),  # nk > 1 (pipe block_k 512 vs block_q 1280)
        (1280, 1280, 2, 1100),  # nk > 1 + phases + ragged tail
    ],
)
def test_pipelined_fwd_matches_serial(rng, monkeypatch, L, sl, r, rl):
    """GIGAPATH_PIPELINED_ATTN forward == the serial fused kernel.

    The pipelined kernel computes cell n's logits while consuming cell
    n-1's from a parity scratch (v/out index maps lag one step); same
    online-softmax math, so outputs agree to fp32 rounding even when the
    k-block split differs."""
    from gigapath_tpu.ops.pallas_dilated import dilated_branch_attention

    H, Dh = 8, 16
    E = H * Dh
    q, k, v = (
        jnp.asarray(rng.normal(size=(2, L, E)), jnp.float32) for _ in range(3)
    )
    monkeypatch.delenv("GIGAPATH_PIPELINED_ATTN", raising=False)
    o0, l0 = dilated_branch_attention(q, k, v, sl, r, H, real_len=rl, interpret=True)
    monkeypatch.setenv("GIGAPATH_PIPELINED_ATTN", "1")
    monkeypatch.setenv("GIGAPATH_PIPE_BLOCK_K", "512")
    o1, l1 = dilated_branch_attention(q, k, v, sl, r, H, real_len=rl, interpret=True)
    np.testing.assert_allclose(np.asarray(o1), np.asarray(o0), atol=2e-6, rtol=1e-5)
    fin = np.asarray(l0) > -1e19  # uncovered slots hold sentinels
    np.testing.assert_allclose(
        np.asarray(l1)[fin], np.asarray(l0)[fin], atol=2e-6, rtol=1e-5
    )


@pytest.mark.slow
@pytest.mark.parametrize(
    "L,sl,r,rl",
    [
        (300, 512, 2, 277),   # tail block straddles L; ragged real_len
        (523, 1024, 4, 523),  # L far from a bt*r multiple
        (260, 4096, 8, 201),  # hb == 1 band
    ],
)
def test_pack_direct_matches_padded(rng, monkeypatch, L, sl, r, rl):
    """GIGAPATH_PACK_DIRECT (single-segment branches read/write dense
    [B, L, E] directly, re-tiling in VMEM) must be bit-identical to the
    padded-view path, forward and backward."""
    from gigapath_tpu.ops.pallas_dilated import dilated_branch_attention

    H, Dh = 8, 16
    E = H * Dh
    q, k, v = (
        jnp.asarray(rng.normal(size=(2, L, E)), jnp.float32) for _ in range(3)
    )

    def loss(q_, k_, v_):
        o, _ = dilated_branch_attention(
            q_, k_, v_, sl, r, H, real_len=rl, interpret=True
        )
        return (o * o).sum()

    monkeypatch.delenv("GIGAPATH_PACK_DIRECT", raising=False)
    o0, l0 = dilated_branch_attention(q, k, v, sl, r, H, real_len=rl, interpret=True)
    g0 = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    monkeypatch.setenv("GIGAPATH_PACK_DIRECT", "1")
    o1, l1 = dilated_branch_attention(q, k, v, sl, r, H, real_len=rl, interpret=True)
    g1 = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    np.testing.assert_array_equal(np.asarray(o1), np.asarray(o0))
    fin = np.asarray(l0) > -1e19
    np.testing.assert_array_equal(np.asarray(l1)[fin], np.asarray(l0)[fin])
    for a, b in zip(g1, g0):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.slow
def test_pack_direct_fully_oob_tail_block(rng, monkeypatch):
    """Regression: at the flagship-like fp32 r=16 geometry the VMEM budget
    drops the copy-kernel row block to bt=64, and m=129 pads to Mp=256 —
    so the direct unpack's naive grid would contain a block STARTING past
    L (2064 < 3*1024 < 4*1024 = Mp*r). Pallas clamps such a block
    backward (dynamic-slice semantics), overwriting the last valid rows
    with padded-row garbage; the grid must exclude it."""
    from gigapath_tpu.ops.pallas_dilated import _pack_bt, dilated_branch_attention

    H, Dh, r, L, sl = 16, 48, 16, 2064, 4096
    E = H * Dh
    assert _pack_bt(256, r, E, 4) == 64  # the geometry the test relies on
    q, k, v = (
        jnp.asarray(rng.normal(size=(1, L, E)), jnp.float32) for _ in range(3)
    )
    monkeypatch.delenv("GIGAPATH_PACK_DIRECT", raising=False)
    o0, _ = dilated_branch_attention(q, k, v, sl, r, H, interpret=True)
    monkeypatch.setenv("GIGAPATH_PACK_DIRECT", "1")
    o1, _ = dilated_branch_attention(q, k, v, sl, r, H, interpret=True)
    np.testing.assert_array_equal(np.asarray(o1), np.asarray(o0))


@pytest.mark.slow
@pytest.mark.parametrize(
    "L,sl,r,rl",
    [
        (300, 64, 2, 277),      # multi-segment, phases, ragged tail
        (1280, 1280, 1, 1280),  # bwd pipe block_k 512 -> nk=3
        (1280, 1280, 2, 1100),
        (300, 64, 2, "traced"),  # TRACED per-batch valid lengths (the
        #                          collate pad-mask mode of the train path)
    ],
)
def test_pipelined_bwd_matches_serial(rng, monkeypatch, L, sl, r, rl):
    """GIGAPATH_PIPELINED_BWD gradients == the serial backward kernels to
    fp32 rounding (the pipelined kernels fold scale*log2(e) into q before
    the logits matmul, as the forward does, instead of scaling the
    [bq, bk] tile)."""
    from gigapath_tpu.ops.pallas_dilated import dilated_branch_attention

    H, Dh = 8, 16
    E = H * Dh
    q, k, v = (
        jnp.asarray(rng.normal(size=(2, L, E)), jnp.float32) for _ in range(3)
    )
    mask_kw = (
        {"valid_len_dyn": jnp.asarray([L, 211], jnp.int32)}
        if rl == "traced"
        else {"real_len": rl}
    )

    def loss(q_, k_, v_):
        o, _ = dilated_branch_attention(
            q_, k_, v_, sl, r, H, interpret=True, **mask_kw
        )
        return (o * o).sum()

    monkeypatch.delenv("GIGAPATH_PIPELINED_BWD", raising=False)
    g0 = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    monkeypatch.setenv("GIGAPATH_PIPELINED_BWD", "1")
    g1 = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g0):
        scale = max(float(jnp.max(jnp.abs(np.asarray(b)))), 1e-12)
        np.testing.assert_allclose(
            np.asarray(a) / scale, np.asarray(b) / scale, atol=2e-6
        )


def test_pipelined_fwd_fast_small_geometry(rng, monkeypatch):
    """Fast default-tier sibling of test_pipelined_fwd_matches_serial:
    one L=300/nk==1 case so ``pytest -q`` exercises the
    GIGAPATH_PIPELINED_ATTN kernel path on every run (the round-5 slow-only
    gap gigalint GL005 now guards against)."""
    from gigapath_tpu.ops.pallas_dilated import dilated_branch_attention

    L, sl, r, rl = 300, 64, 1, 300
    H, Dh = 8, 16
    E = H * Dh
    q, k, v = (
        jnp.asarray(rng.normal(size=(1, L, E)), jnp.float32) for _ in range(3)
    )
    monkeypatch.delenv("GIGAPATH_PIPELINED_ATTN", raising=False)
    o0, l0 = dilated_branch_attention(q, k, v, sl, r, H, real_len=rl, interpret=True)
    monkeypatch.setenv("GIGAPATH_PIPELINED_ATTN", "1")
    monkeypatch.setenv("GIGAPATH_PIPE_BLOCK_K", "512")
    o1, l1 = dilated_branch_attention(q, k, v, sl, r, H, real_len=rl, interpret=True)
    np.testing.assert_allclose(np.asarray(o1), np.asarray(o0), atol=2e-6, rtol=1e-5)
    fin = np.asarray(l0) > -1e19
    np.testing.assert_allclose(
        np.asarray(l1)[fin], np.asarray(l0)[fin], atol=2e-6, rtol=1e-5
    )


def test_pipelined_bwd_fast_small_geometry(rng, monkeypatch):
    """Fast default-tier sibling of test_pipelined_bwd_matches_serial
    (GIGAPATH_PIPELINED_BWD): one small multi-phase ragged-tail case."""
    from gigapath_tpu.ops.pallas_dilated import dilated_branch_attention

    L, sl, r, rl = 128, 32, 2, 101
    H, Dh = 4, 16
    E = H * Dh
    q, k, v = (
        jnp.asarray(rng.normal(size=(1, L, E)), jnp.float32) for _ in range(3)
    )

    def loss(q_, k_, v_):
        o, _ = dilated_branch_attention(
            q_, k_, v_, sl, r, H, real_len=rl, interpret=True
        )
        return (o * o).sum()

    monkeypatch.delenv("GIGAPATH_PIPELINED_BWD", raising=False)
    g0 = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    monkeypatch.setenv("GIGAPATH_PIPELINED_BWD", "1")
    g1 = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g0):
        scale = max(float(jnp.max(jnp.abs(np.asarray(b)))), 1e-12)
        np.testing.assert_allclose(
            np.asarray(a) / scale, np.asarray(b) / scale, atol=2e-6
        )


def test_pack_direct_fast_small_geometry(rng, monkeypatch):
    """Fast default-tier sibling of test_pack_direct_matches_padded
    (GIGAPATH_PACK_DIRECT): single-segment branch with a straddling tail
    block, forward bit-identity only (the slow tier covers gradients)."""
    from gigapath_tpu.ops.pallas_dilated import dilated_branch_attention

    L, sl, r, rl = 300, 512, 2, 277
    H, Dh = 8, 16
    E = H * Dh
    q, k, v = (
        jnp.asarray(rng.normal(size=(1, L, E)), jnp.float32) for _ in range(3)
    )
    monkeypatch.delenv("GIGAPATH_PACK_DIRECT", raising=False)
    o0, l0 = dilated_branch_attention(q, k, v, sl, r, H, real_len=rl, interpret=True)
    monkeypatch.setenv("GIGAPATH_PACK_DIRECT", "1")
    o1, l1 = dilated_branch_attention(q, k, v, sl, r, H, real_len=rl, interpret=True)
    np.testing.assert_array_equal(np.asarray(o1), np.asarray(o0))
    fin = np.asarray(l0) > -1e19
    np.testing.assert_array_equal(np.asarray(l1)[fin], np.asarray(l0)[fin])


def test_seq_parallel_fused_routing_fast(rng, monkeypatch):
    """Fast default-tier sibling of the seq-parallel fused-routing slow
    tests: a 2-device mesh at tiny geometry still routes fits-local
    branches through the fused kernels and matches single-device."""
    import functools

    from jax.sharding import Mesh, PartitionSpec as P

    import gigapath_tpu.ops.flash_attention as fa
    import gigapath_tpu.ops.pallas_dilated as pdm
    from gigapath_tpu.ops import dilated_attention as da

    monkeypatch.setattr(fa, "_on_tpu", lambda: True)
    real = pdm.dilated_branch_attention
    routed = []

    def spy(q, k, v, sl, r, H, **kw):
        routed.append((sl, r, kw.get("real_len")))
        kw["interpret"] = True
        return real(q, k, v, sl, r, H, **kw)

    monkeypatch.setattr(pdm, "dilated_branch_attention", spy)

    n_dev = 2
    B, L, H, Dh = 1, 64, 4, 8
    sls, drs = [8, 32], [1, 2]  # both fit the 32-token local shard
    q, k, v = (
        jnp.asarray(rng.normal(size=(B, L, H, Dh)), jnp.float32)
        for _ in range(3)
    )
    single = da.dilated_attention(q, k, v, sls, drs)
    routed.clear()

    mesh = Mesh(np.array(jax.devices()[:n_dev]), ("seq",))
    # rep/vma checking can't see through pallas_call on either jax line —
    # disabled exactly as in the slow seq-parallel tests
    shard_map, check_kw = _shard_map_compat()
    fn = shard_map(
        functools.partial(
            da.dilated_attention, segment_lengths=sls, dilated_ratios=drs,
            seq_axis_name="seq", seq_axis_size=n_dev,
        ),
        mesh=mesh,
        in_specs=(P(None, "seq"), P(None, "seq"), P(None, "seq")),
        out_specs=P(None, "seq"),
        **check_kw,
    )
    sharded = fn(q, k, v)
    assert len(routed) == len(sls), (
        f"both local branches should take the fused path, got {routed}"
    )
    assert all(rl == L // n_dev for _, _, rl in routed)
    np.testing.assert_allclose(
        np.asarray(sharded), np.asarray(single), atol=2e-5, rtol=1e-4
    )


@pytest.mark.slow
def test_seq_parallel_local_branches_use_fused_path(rng, monkeypatch):
    """Under sequence parallelism, branches whose segment fits the local
    shard route through the fused phase-major kernels (the single-chip
    default) and still match the single-device result. _on_tpu is
    monkeypatched True with interpret-mode kernels so the TPU-only
    dispatch runs on the CPU mesh."""
    import functools

    from jax.sharding import Mesh, PartitionSpec as P

    import gigapath_tpu.ops.flash_attention as fa
    import gigapath_tpu.ops.pallas_dilated as pdm
    from gigapath_tpu.ops import dilated_attention as da

    monkeypatch.setattr(fa, "_on_tpu", lambda: True)
    real = pdm.dilated_branch_attention
    routed = []

    def spy(q, k, v, sl, r, H, **kw):
        routed.append((sl, r, kw.get("real_len")))
        kw["interpret"] = True
        return real(q, k, v, sl, r, H, **kw)

    monkeypatch.setattr(pdm, "dilated_branch_attention", spy)

    n_dev = 8
    B, L, H, Dh = 1, 1024, 4, 8
    sls, drs = [32, 128], [1, 2]
    q, k, v = (
        jnp.asarray(rng.normal(size=(B, L, H, Dh)), jnp.float32)
        for _ in range(3)
    )
    single = da.dilated_attention(q, k, v, sls, drs)
    assert routed, "single-device fast path should also route via the spy"
    routed.clear()

    mesh = Mesh(np.array(jax.devices()[:n_dev]), ("seq",))
    fn = jax.shard_map(
        functools.partial(
            da.dilated_attention, segment_lengths=sls, dilated_ratios=drs,
            seq_axis_name="seq", seq_axis_size=n_dev,
        ),
        mesh=mesh,
        in_specs=(P(None, "seq"), P(None, "seq"), P(None, "seq")),
        out_specs=P(None, "seq"),
        # jax 0.9's vma checking cannot yet see through pallas_call
        # (out_shape avals carry no vma); jax's own guidance is
        # check_vma=False for shard_map regions hosting pallas kernels
        check_vma=False,
    )
    sharded = fn(q, k, v)
    assert len(routed) == len(sls), (
        f"both local branches should take the fused path, got {routed}"
    )
    assert all(rl == L // n_dev for _, _, rl in routed)
    np.testing.assert_allclose(
        np.asarray(sharded), np.asarray(single), atol=2e-5, rtol=1e-4
    )


@pytest.mark.slow
def test_seq_parallel_mixed_fused_and_gathered_branches(rng, monkeypatch):
    """One cross-branch softmax fusion mixing a fused-kernel local branch
    (Pallas lse convention) with a gathered branch computed by the generic
    path (sparse_to_dense lse) must match the single-device result — the
    two lse conventions may never drift apart. The gathered branch's
    sparse length stays under PALLAS_MIN_SEQ so it runs the jnp tier even
    with _on_tpu patched True."""
    import functools

    from jax.sharding import Mesh, PartitionSpec as P

    import gigapath_tpu.ops.flash_attention as fa
    import gigapath_tpu.ops.pallas_dilated as pdm
    from gigapath_tpu.ops import dilated_attention as da

    monkeypatch.setattr(fa, "_on_tpu", lambda: True)
    real = pdm.dilated_branch_attention
    routed = []

    def spy(q, k, v, sl, r, H, **kw):
        routed.append(sl)
        kw["interpret"] = True
        return real(q, k, v, sl, r, H, **kw)

    monkeypatch.setattr(pdm, "dilated_branch_attention", spy)

    n_dev = 8
    B, L, H, Dh = 1, 1024, 4, 8
    sls, drs = [32, 512], [1, 2]  # 512 > local 128 -> gathered, m=256 jnp tier
    q, k, v = (
        jnp.asarray(rng.normal(size=(B, L, H, Dh)), jnp.float32)
        for _ in range(3)
    )
    single = da.dilated_attention(q, k, v, sls, drs)
    routed.clear()

    mesh = Mesh(np.array(jax.devices()[:n_dev]), ("seq",))
    fn = jax.shard_map(
        functools.partial(
            da.dilated_attention, segment_lengths=sls, dilated_ratios=drs,
            seq_axis_name="seq", seq_axis_size=n_dev,
        ),
        mesh=mesh,
        in_specs=(P(None, "seq"), P(None, "seq"), P(None, "seq")),
        out_specs=P(None, "seq"),
        check_vma=False,
    )
    sharded = fn(q, k, v)
    assert routed == [32], f"only the local branch routes fused, got {routed}"
    np.testing.assert_allclose(
        np.asarray(sharded), np.asarray(single), atol=2e-5, rtol=1e-4
    )


@pytest.mark.slow
def test_seq_parallel_vma_checked_falls_back_generic(rng, monkeypatch):
    """Inside a DEFAULT (check_vma=True) shard_map the fused-local routing
    must auto-fall-back to the generic path (pallas is vma-opaque in
    jax 0.9) instead of hard-failing existing callers."""
    import functools

    from jax.sharding import Mesh, PartitionSpec as P

    import gigapath_tpu.ops.flash_attention as fa
    import gigapath_tpu.ops.pallas_dilated as pdm
    from gigapath_tpu.ops import dilated_attention as da

    monkeypatch.setattr(fa, "_on_tpu", lambda: True)
    real = pdm.dilated_branch_attention

    def interp(q, k, v, sl, r, H, **kw):
        kw["interpret"] = True
        return real(q, k, v, sl, r, H, **kw)

    monkeypatch.setattr(pdm, "dilated_branch_attention", interp)

    n_dev = 8
    B, L, H, Dh = 1, 512, 4, 8
    q, k, v = (
        jnp.asarray(rng.normal(size=(B, L, H, Dh)), jnp.float32)
        for _ in range(3)
    )
    single = da.dilated_attention(q, k, v, [32], [1])

    def boom(*a, **kw):
        raise AssertionError("fused path must not run under check_vma=True")

    monkeypatch.setattr(pdm, "dilated_branch_attention", boom)

    mesh = Mesh(np.array(jax.devices()[:n_dev]), ("seq",))
    fn = jax.shard_map(
        functools.partial(
            da.dilated_attention, segment_lengths=[32], dilated_ratios=[1],
            seq_axis_name="seq", seq_axis_size=n_dev,
        ),
        mesh=mesh,
        in_specs=(P(None, "seq"), P(None, "seq"), P(None, "seq")),
        out_specs=P(None, "seq"),
    )
    sharded = fn(q, k, v)  # must not raise
    np.testing.assert_allclose(
        np.asarray(sharded), np.asarray(single), atol=2e-5, rtol=1e-4
    )



# ---------------------------------------------------------------------------
# streaming cross-branch fusion epilogue (GIGAPATH_STREAM_FUSION)
# ---------------------------------------------------------------------------


class TestStreamFusionEpilogue:
    """Interpret-mode parity of the packed streaming fusion epilogue
    against the dense scatter + stacked-softmax path (the parity oracle
    it replaces on the hot path). Fast default tier: every ``pytest -q``
    verifies the epilogue even while the chip tunnel is down."""

    def _qkv(self, rng, B, L, H, Dh, dtype=jnp.float32):
        return tuple(
            jnp.asarray(rng.normal(size=(B, L, H, Dh)), dtype)
            for _ in range(3)
        )

    def _paths(self, q, k, v, sls, drs, **kw):
        from gigapath_tpu.ops.dilated_attention import dilated_attention_fused
        from gigapath_tpu.ops.pallas_dilated import PipelineFlags

        dense = dilated_attention_fused(
            q, k, v, sls, drs, interpret=True, **kw
        )
        stream = dilated_attention_fused(
            q, k, v, sls, drs, interpret=True,
            flags=PipelineFlags(stream_fusion=True), **kw
        )
        return dense, stream

    def test_fwd_parity_ragged_tail(self, rng):
        """ISSUE geometry: L=300, 2 branches, ragged tail — fused forward
        within 1e-5 of the dense-fusion path."""
        q, k, v = self._qkv(rng, 1, 300, 4, 8)
        dense, stream = self._paths(q, k, v, [256, 512], [1, 2], valid_len=277)
        np.testing.assert_allclose(
            np.asarray(stream), np.asarray(dense), atol=1e-5, rtol=1e-5
        )

    def test_fwd_parity_uncovered_slots(self, rng):
        """No r=1 branch: (token, head) slots covered by NO branch must
        produce the same (zero) output as the dense path's uniform-softmax-
        over-NEG_INF convention."""
        q, k, v = self._qkv(rng, 1, 128, 4, 8)
        dense, stream = self._paths(q, k, v, [64, 128], [2, 4])
        np.testing.assert_allclose(
            np.asarray(stream), np.asarray(dense), atol=1e-5, rtol=1e-5
        )
        # sanity: uncovered slots exist and are exactly zero on both paths
        assert (np.asarray(dense) == 0).any()

    def test_grad_parity_ragged_tail(self, rng):
        """Epilogue backward (packed d_out per branch via re-derived
        weights) within 1e-4 of the dense path's gradients."""
        from gigapath_tpu.ops.dilated_attention import dilated_attention_fused
        from gigapath_tpu.ops.pallas_dilated import PipelineFlags

        q, k, v = self._qkv(rng, 1, 300, 4, 8)
        vl = jnp.asarray([277], jnp.int32)  # traced ragged tail

        def grads(flags):
            def loss(q, k, v):
                o = dilated_attention_fused(
                    q, k, v, [256, 512], [1, 2], valid_len=vl,
                    interpret=True, flags=flags,
                )
                return (o.astype(jnp.float32) ** 2).sum()

            return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

        g_dense = grads(PipelineFlags())
        g_stream = grads(PipelineFlags(stream_fusion=True))
        for a, b in zip(g_dense, g_stream):
            np.testing.assert_allclose(
                np.asarray(b), np.asarray(a), atol=1e-4, rtol=1e-4
            )

    def test_multiclass_state_chain(self, rng):
        """A segment length not sharing an alignment with the other
        branch (g=24 vs the pow-2 blocks) forces two epilogue classes —
        the compact (acc, m, l) state hand-off between passes must be
        exact, forward and backward."""
        from gigapath_tpu.ops.dilated_attention import dilated_attention_fused
        from gigapath_tpu.ops.pallas_dilated import (
            PipelineFlags, plan_stream_fusion,
        )

        B, L, H, Dh = 1, 48, 2, 8
        plan = plan_stream_fusion(L, H * Dh, H, [24, 64], [1, 2])
        assert plan is not None and len(plan.classes) == 2, plan
        q, k, v = self._qkv(rng, B, L, H, Dh)
        dense, stream = self._paths(q, k, v, [24, 64], [1, 2])
        np.testing.assert_allclose(
            np.asarray(stream), np.asarray(dense), atol=1e-5, rtol=1e-5
        )

        def grads(flags):
            def loss(q, k, v):
                o = dilated_attention_fused(
                    q, k, v, [24, 64], [1, 2], interpret=True, flags=flags,
                )
                return (o.astype(jnp.float32) ** 2).sum()

            return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

        for a, b in zip(grads(PipelineFlags()),
                        grads(PipelineFlags(stream_fusion=True))):
            np.testing.assert_allclose(
                np.asarray(b), np.asarray(a), atol=1e-4, rtol=1e-4
            )

    def test_env_flag_snapshot_routes_epilogue(self, rng, monkeypatch):
        """GIGAPATH_STREAM_FUSION rides the PipelineFlags snapshot into
        the epilogue path (un-jitted call: retraces per call, so the env
        monkeypatch is visible)."""
        from gigapath_tpu.ops.dilated_attention import dilated_attention_fused
        from gigapath_tpu.ops import pallas_dilated as pdm

        calls = []
        real = pdm._fusion_epilogue

        def spy(outs, lses, plan):
            calls.append(plan)
            return real(outs, lses, plan)

        monkeypatch.setattr(pdm, "_fusion_epilogue", spy)
        monkeypatch.setenv("GIGAPATH_STREAM_FUSION", "1")
        q, k, v = self._qkv(rng, 1, 64, 4, 8)
        out = dilated_attention_fused(q, k, v, [32, 64], [1, 2], interpret=True)
        assert calls, "flagged call must route through the fusion epilogue"
        monkeypatch.setenv("GIGAPATH_STREAM_FUSION", "0")
        calls.clear()
        ref = dilated_attention_fused(q, k, v, [32, 64], [1, 2], interpret=True)
        assert not calls
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5
        )

    def test_flag_keys_do_not_alias(self, rng):
        """Zero-retrace contract: epilogue on/off are DISTINCT PipelineFlags
        static keys — two jit cache entries, no silent aliasing of a trace
        made under the other flag value."""
        import functools

        from gigapath_tpu.ops.dilated_attention import dilated_attention_fused
        from gigapath_tpu.ops.pallas_dilated import PipelineFlags

        @functools.partial(jax.jit, static_argnums=(3,))
        def f(q, k, v, flags):
            return dilated_attention_fused(
                q, k, v, [64, 128], [1, 2], interpret=True, flags=flags,
            )

        q, k, v = self._qkv(rng, 1, 128, 4, 8)
        a = f(q, k, v, PipelineFlags(stream_fusion=True))
        b = f(q, k, v, PipelineFlags())
        assert f._cache_size() == 2, (
            "stream_fusion on/off must trace under distinct cache keys"
        )
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-5
        )

    def test_infeasible_plan_falls_back_to_dense(self, rng):
        """Geometry with no legal epilogue blocking (g=12 divides no
        candidate block) silently uses the dense fusion path."""
        from gigapath_tpu.ops.pallas_dilated import (
            PipelineFlags, plan_stream_fusion,
        )

        assert plan_stream_fusion(24, 32, 4, [12, 32], [1, 2]) is None
        q, k, v = self._qkv(rng, 1, 24, 4, 8)
        dense, stream = self._paths(q, k, v, [12, 32], [1, 2])
        np.testing.assert_allclose(
            np.asarray(stream), np.asarray(dense), atol=1e-6, rtol=1e-6
        )


def test_stream_fusion_jaxpr_has_no_dense_branch_lse(rng):
    """Regression guard (acceptance): with the epilogue on, the traced
    flagship-style program contains NO dense per-branch [B, H, L] lse
    intermediate — the glue cannot silently reappear. The dense path is
    the positive control (it must still materialize them)."""
    from gigapath_tpu.ops.dilated_attention import dilated_attention_fused
    from gigapath_tpu.ops.pallas_dilated import (
        PipelineFlags, plan_stream_fusion,
    )

    B, L, H, Dh = 1, 512, 16, 4
    sls = [1024, 5792, 32768, 185363, 1048576]  # flagship schedule
    drs = [1, 2, 4, 8, 16]
    assert plan_stream_fusion(L, H * Dh, H, sls, drs) is not None
    q, k, v = (
        jnp.asarray(rng.normal(size=(B, L, H, Dh)), jnp.float32)
        for _ in range(3)
    )

    def trace(flags, grad=False):
        def f(q, k, v):
            o = dilated_attention_fused(
                q, k, v, sls, drs, interpret=True, flags=flags,
            )
            return (o.astype(jnp.float32) ** 2).sum()

        fn = jax.grad(f) if grad else f
        return str(jax.make_jaxpr(fn)(q, k, v))

    dense_lse = f"f32[{B},{H},{L}]"
    for grad in (False, True):
        on = trace(PipelineFlags(stream_fusion=True), grad)
        off = trace(PipelineFlags(), grad)
        assert dense_lse not in on, (
            f"dense per-branch lse reappeared in the epilogue trace "
            f"(grad={grad})"
        )
        assert dense_lse in off, (
            "positive control broke: the dense path should materialize "
            f"per-branch [B, H, L] lse tensors (grad={grad})"
        )


def test_seq_parallel_ragged_mask_fused_routing(rng, monkeypatch):
    """VERDICT weak #4 closed: a ragged key_padding_mask (traced per-shard
    valid counts) under sequence parallelism routes segment-local branches
    through the fused kernels — not the generic fallback — and the
    gathered branch masks its all-gathered keys from the per-rank counts.
    Loss and grads match the single-device result."""
    import functools

    from jax.sharding import Mesh, PartitionSpec as P

    import gigapath_tpu.ops.flash_attention as fa
    import gigapath_tpu.ops.pallas_dilated as pdm
    from gigapath_tpu.ops import dilated_attention as da

    monkeypatch.setattr(fa, "_on_tpu", lambda: True)
    real = pdm.dilated_branch_attention
    routed = []

    def spy(q, k, v, sl, r, H, **kw):
        routed.append((sl, kw.get("valid_len_dyn") is not None))
        kw["interpret"] = True
        return real(q, k, v, sl, r, H, **kw)

    monkeypatch.setattr(pdm, "dilated_branch_attention", spy)

    n_dev = 2
    B, L, H, Dh = 1, 32, 4, 8
    sls, drs = [8, 32], [1, 2]  # 8 fits the 16-token shard; 32 gathers
    valid = 25
    q, k, v = (
        jnp.asarray(rng.normal(size=(B, L, H, Dh)), jnp.float32)
        for _ in range(3)
    )
    pad_mask = jnp.arange(L)[None, :] >= valid  # True = pad (collate)
    vmask = (~pad_mask).astype(jnp.float32)[:, :, None, None]

    def single_loss(q, k, v):
        out = da.dilated_attention(
            q, k, v, sls, drs,
            valid_len=jnp.full((B,), valid, jnp.int32),
        )
        return ((out.astype(jnp.float32) * vmask) ** 2).sum()

    single = single_loss(q, k, v)
    g_single = jax.grad(single_loss, argnums=(0, 1, 2))(q, k, v)
    assert routed, "single-device fused path must route via the spy"
    routed.clear()

    mesh = Mesh(np.array(jax.devices()[:n_dev]), ("seq",))
    shard_map, check_kw = _shard_map_compat()

    def local_fn(q, k, v, mask_local):
        # per-shard valid counts from the SHARDED mask — exactly what
        # DilatedAttention._attend derives under shard_map
        vl = (~mask_local).sum(axis=-1).astype(jnp.int32)
        return da.dilated_attention(
            q, k, v, sls, drs, seq_axis_name="seq", seq_axis_size=n_dev,
            valid_len=vl,
        )

    fn = shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(P(None, "seq"),) * 3 + (P(None, "seq"),),
        out_specs=P(None, "seq"),
        **check_kw,
    )

    def sharded_loss(q, k, v):
        out = fn(q, k, v, pad_mask)
        return ((out.astype(jnp.float32) * vmask) ** 2).sum()

    sharded = sharded_loss(q, k, v)
    g_sharded = jax.grad(sharded_loss, argnums=(0, 1, 2))(q, k, v)
    fused_routed = [e for e in routed if e[0] == 8]
    assert fused_routed and all(has_vl for _, has_vl in fused_routed), (
        f"ragged local branch must route fused WITH valid counts: {routed}"
    )
    assert all(sl != 64 for sl, _ in routed), (
        f"the gathered branch must not route through the fused kernels: "
        f"{routed}"
    )
    np.testing.assert_allclose(
        float(sharded), float(single), rtol=1e-5
    )
    for a, b in zip(g_single, g_sharded):
        np.testing.assert_allclose(
            np.asarray(b), np.asarray(a), atol=2e-5, rtol=1e-4
        )


# ---------------------------------------------------------------------------
# ring-scheduled sequence parallelism (GIGAPATH_RING_ATTN)
# ---------------------------------------------------------------------------


def _shard_map_compat():
    """(shard_map, check kwarg) across jax spellings/signatures."""
    from gigapath_tpu.parallel.sharding import shard_map_compat

    return shard_map_compat()


def _seq_parallel_fn(mesh, ndev, sls, drs, flags, n_arrays=3):
    """shard_map'd dilated_attention over a seq axis of ``ndev`` ranks."""
    from jax.sharding import PartitionSpec as P

    shard_map, check_kw = _shard_map_compat()
    return shard_map(
        lambda q, k, v: dilated_attention(
            q, k, v, sls, drs, seq_axis_name="seq", seq_axis_size=ndev,
            flags=flags,
        ),
        mesh=mesh,
        in_specs=(P(None, "seq"),) * n_arrays,
        out_specs=P(None, "seq"),
        **check_kw,
    )


def _qkv3(rng, B, N, H, D):
    return tuple(
        jnp.asarray(rng.normal(size=(B, N, H, D)), jnp.float32)
        for _ in range(3)
    )


def test_ring_matches_gather_seq_parallel(rng):
    """Core ring acceptance, compact tier: on a 2-way seq mesh the
    ring-scheduled gathered branch matches the all-gather path (the
    parity oracle) AND the single-device op — forward 1e-5, grads 1e-4.
    The 8-way mesh with a sub-mesh segment is the slow-tier sibling
    (test_ring_matches_gather_8way_submesh)."""
    from jax.sharding import Mesh

    from gigapath_tpu.ops.pallas_dilated import PipelineFlags

    mesh = Mesh(np.array(jax.devices()[:2]), ("seq",))
    q, k, v = _qkv3(rng, 1, 16, 4, 8)
    sls, drs = [4, 16], [1, 2]  # 16 > the 8-token shard: rps=2 ring

    ref = dilated_attention(q, k, v, sls, drs)
    gather_fn = _seq_parallel_fn(mesh, 2, sls, drs, PipelineFlags())
    ring_fn = _seq_parallel_fn(
        mesh, 2, sls, drs, PipelineFlags(ring_attn=True)
    )
    out_g = gather_fn(q, k, v)
    out_r = ring_fn(q, k, v)
    np.testing.assert_allclose(np.asarray(out_r), np.asarray(out_g), atol=1e-5)
    np.testing.assert_allclose(np.asarray(out_r), np.asarray(ref), atol=1e-5)

    def grads(fn):
        def loss(q, k, v):
            return (fn(q, k, v).astype(jnp.float32) ** 2).sum()

        return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

    for a, b in zip(grads(gather_fn), grads(ring_fn)):
        np.testing.assert_allclose(
            np.asarray(b), np.asarray(a), atol=1e-4, rtol=1e-4
        )


@pytest.mark.slow
def test_ring_matches_gather_8way_submesh(rng):
    """8-way mesh, segments spanning BOTH a strict subset of the mesh
    (sl=16 over 4-token shards: rps=4 < world=8 — two independent
    sub-rings) and the full mesh (sl=32: rps=8): ring output and grads
    match the all-gather path and the single-device op."""
    from jax.sharding import Mesh

    from gigapath_tpu.ops.pallas_dilated import PipelineFlags

    mesh = Mesh(np.array(jax.devices()[:8]), ("seq",))
    q, k, v = _qkv3(rng, 1, 32, 4, 8)
    sls, drs = [4, 16, 32], [1, 2, 4]

    ref = dilated_attention(q, k, v, sls, drs)
    gather_fn = _seq_parallel_fn(mesh, 8, sls, drs, PipelineFlags())
    ring_fn = _seq_parallel_fn(
        mesh, 8, sls, drs, PipelineFlags(ring_attn=True)
    )
    out_r = ring_fn(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out_r), np.asarray(gather_fn(q, k, v)), atol=1e-5
    )
    np.testing.assert_allclose(np.asarray(out_r), np.asarray(ref), atol=1e-5)

    def grads(fn):
        def loss(q, k, v):
            return (fn(q, k, v).astype(jnp.float32) ** 2).sum()

        return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

    for a, b in zip(grads(gather_fn), grads(ring_fn)):
        np.testing.assert_allclose(
            np.asarray(b), np.asarray(a), atol=1e-4, rtol=1e-4
        )


def _ragged_seq_parallel_fn(mesh, ndev, sls, drs, flags):
    """shard_map'd dilated_attention deriving per-shard valid counts from
    the SHARDED pad mask — what DilatedAttention._attend does."""
    from jax.sharding import PartitionSpec as P

    shard_map, check_kw = _shard_map_compat()

    def local(q, k, v, mask):
        vls = (~mask).sum(axis=-1).astype(jnp.int32)
        return dilated_attention(
            q, k, v, sls, drs, seq_axis_name="seq", seq_axis_size=ndev,
            valid_len=vls, flags=flags,
        )

    return shard_map(
        local, mesh=mesh, in_specs=(P(None, "seq"),) * 4,
        out_specs=P(None, "seq"), **check_kw,
    )


def test_ring_ragged_mask_matches_single_device(rng):
    """Ragged key_padding_mask under the ring: per-ORIGIN-rank valid
    counts (from the hoisted per-call counts gather) mask each resident
    chunk, matching the single-device op at valid positions. Also pins
    the hoist itself: the ragged ring trace carries exactly ONE
    all_gather (the counts — shared by BOTH gathered branches) and the
    gather path's K/V all_gathers are gone."""
    from jax.sharding import Mesh

    from gigapath_tpu.obs import jaxpr_fingerprint
    from gigapath_tpu.ops.pallas_dilated import PipelineFlags

    mesh = Mesh(np.array(jax.devices()[:2]), ("seq",))
    B, N, valid = 1, 32, 25
    q, k, v = _qkv3(rng, B, N, 4, 8)
    sls, drs = [8, 32, 32], [1, 2, 4]  # TWO gathered branches share the hoist
    pad = jnp.arange(N)[None, :] >= valid
    vmask = (~pad).astype(np.float32)[:, :, None, None]

    ref = dilated_attention(
        q, k, v, sls, drs, valid_len=jnp.full((B,), valid, jnp.int32)
    )
    ring_fn = _ragged_seq_parallel_fn(
        mesh, 2, sls, drs, PipelineFlags(ring_attn=True)
    )
    out_r = ring_fn(q, k, v, pad)
    np.testing.assert_allclose(
        np.asarray(out_r) * np.asarray(vmask),
        np.asarray(ref) * np.asarray(vmask), atol=1e-5,
    )

    gather_fn = _ragged_seq_parallel_fn(mesh, 2, sls, drs, PipelineFlags())
    fp_ring = jaxpr_fingerprint(
        lambda q, k, v: ring_fn(q, k, v, pad), q, k, v
    )["primitives"]
    fp_gather = jaxpr_fingerprint(
        lambda q, k, v: gather_fn(q, k, v, pad), q, k, v
    )["primitives"]
    assert fp_ring["all_gather"] == 1, fp_ring  # the hoisted counts only
    assert fp_ring["ppermute"] == 4, fp_ring  # 2 branches x (k, v) x (rps-1)
    assert fp_gather["all_gather"] == 5, fp_gather  # counts + 2 x (k, v)
    assert fp_gather["ppermute"] == 0, fp_gather


@pytest.mark.slow
def test_ring_ragged_grads_match_single_device(rng):
    """Slow sibling: gradients through the ragged ring (custom VJP with
    per-origin-rank chunk masking) match the single-device op 1e-4."""
    from jax.sharding import Mesh

    from gigapath_tpu.ops.pallas_dilated import PipelineFlags

    mesh = Mesh(np.array(jax.devices()[:2]), ("seq",))
    B, N, valid = 1, 32, 25
    q, k, v = _qkv3(rng, B, N, 4, 8)
    sls, drs = [8, 32], [1, 2]
    pad = jnp.arange(N)[None, :] >= valid
    vmask = (~pad).astype(jnp.float32)[:, :, None, None]
    vl_full = jnp.full((B,), valid, jnp.int32)
    ring_fn = _ragged_seq_parallel_fn(
        mesh, 2, sls, drs, PipelineFlags(ring_attn=True)
    )

    def single_loss(q, k, v):
        o = dilated_attention(q, k, v, sls, drs, valid_len=vl_full)
        return ((o.astype(jnp.float32) * vmask) ** 2).sum()

    def ring_loss(q, k, v):
        return ((ring_fn(q, k, v, pad).astype(jnp.float32) * vmask) ** 2).sum()

    g_single = jax.grad(single_loss, argnums=(0, 1, 2))(q, k, v)
    g_ring = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_single, g_ring):
        np.testing.assert_allclose(
            np.asarray(b), np.asarray(a), atol=1e-4, rtol=1e-4
        )


def test_ring_jaxpr_no_kv_all_gather(rng):
    """Acceptance fingerprint (trace-only, 8-way): the non-ragged ring
    program contains ZERO all_gather — K/V move exclusively by ppermute,
    one rotation per non-resident chunk per array, sub-ring-sized for the
    subset segment — while the gather path still all-gathers K and V per
    gathered branch. Grad traces: the ring VJP adds the reverse ring's
    permutes, still zero all_gather."""
    from jax.sharding import Mesh

    from gigapath_tpu.obs import jaxpr_fingerprint
    from gigapath_tpu.ops.pallas_dilated import PipelineFlags

    mesh = Mesh(np.array(jax.devices()[:8]), ("seq",))
    q, k, v = _qkv3(rng, 1, 32, 4, 8)
    sls, drs = [4, 16, 32], [1, 2, 4]  # rps 4 (sub-mesh) and 8 (full)

    def fp(flags, grad=False):
        fn = _seq_parallel_fn(mesh, 8, sls, drs, flags)

        def loss(q, k, v):
            return (fn(q, k, v).astype(jnp.float32) ** 2).sum()

        return jaxpr_fingerprint(
            jax.grad(loss, argnums=(0, 1, 2)) if grad else fn, q, k, v
        )["primitives"]

    ring = fp(PipelineFlags(ring_attn=True))
    gather = fp(PipelineFlags())
    assert ring["all_gather"] == 0, ring
    # (rps-1) x (k, v) per gathered branch: (4-1)*2 + (8-1)*2
    assert ring["ppermute"] == 20, ring
    assert gather["all_gather"] == 4, gather  # 2 branches x (k, v)
    assert gather["ppermute"] == 0, gather

    ring_g = fp(PipelineFlags(ring_attn=True), grad=True)
    assert ring_g["all_gather"] == 0, ring_g
    assert ring_g["ppermute"] > ring["ppermute"], ring_g


def test_ring_env_flag_snapshot_routes(rng, monkeypatch):
    """GIGAPATH_RING_ATTN rides the PipelineFlags snapshot into the ring
    dispatch (trace-only: the spy fires at trace time, no mesh compile)."""
    from jax.sharding import Mesh

    from gigapath_tpu.ops import dilated_attention as da
    from gigapath_tpu.ops.pallas_dilated import PipelineFlags

    calls = []
    real = da._ring_attention

    def spy(qs, ks, vs, counts, *static):
        calls.append(static)
        return real(qs, ks, vs, counts, *static)

    monkeypatch.setattr(da, "_ring_attention", spy)
    mesh = Mesh(np.array(jax.devices()[:2]), ("seq",))
    q, k, v = _qkv3(rng, 1, 16, 4, 8)
    fn = _seq_parallel_fn(mesh, 2, [16], [2], None)  # env-snapshot path

    monkeypatch.setenv("GIGAPATH_RING_ATTN", "1")
    jax.make_jaxpr(fn)(q, k, v)
    assert calls, "flagged trace must route through the ring op"

    calls.clear()
    monkeypatch.setenv("GIGAPATH_RING_ATTN", "0")
    jax.make_jaxpr(fn)(q, k, v)
    assert not calls, "unflagged trace must keep the all-gather path"


def test_ring_flag_keys_do_not_alias(rng):
    """Zero-retrace contract: ring on/off are DISTINCT PipelineFlags
    static keys — two jit cache entries, no silent aliasing of a trace
    made under the other flag value."""
    import functools

    from jax.sharding import Mesh

    from gigapath_tpu.ops.pallas_dilated import PipelineFlags

    mesh = Mesh(np.array(jax.devices()[:2]), ("seq",))
    q, k, v = _qkv3(rng, 1, 8, 2, 4)
    sls, drs = [8], [1]  # one gathered branch, the tiniest ring

    @functools.partial(jax.jit, static_argnums=(3,))
    def f(q, k, v, flags):
        return _seq_parallel_fn(mesh, 2, sls, drs, flags)(q, k, v)

    a = f(q, k, v, PipelineFlags(ring_attn=True))
    b = f(q, k, v, PipelineFlags())
    assert f._cache_size() == 2, (
        "ring on/off must trace under distinct cache keys"
    )
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_ring_causal_falls_back_to_gather(rng):
    """A causal gathered branch under the ring flag silently (one
    warning) keeps the all-gather path and stays correct vs the
    single-device causal op."""
    from jax.sharding import Mesh, PartitionSpec as P

    from gigapath_tpu.ops.pallas_dilated import PipelineFlags

    shard_map, check_kw = _shard_map_compat()
    mesh = Mesh(np.array(jax.devices()[:2]), ("seq",))
    q, k, v = _qkv3(rng, 1, 16, 4, 8)
    sls, drs = [16], [2]

    ref = dilated_attention(q, k, v, sls, drs, is_causal=True)
    fn = shard_map(
        lambda q, k, v: dilated_attention(
            q, k, v, sls, drs, is_causal=True, seq_axis_name="seq",
            seq_axis_size=2, flags=PipelineFlags(ring_attn=True),
        ),
        mesh=mesh, in_specs=(P(None, "seq"),) * 3,
        out_specs=P(None, "seq"), **check_kw,
    )
    np.testing.assert_allclose(
        np.asarray(fn(q, k, v)), np.asarray(ref), atol=1e-5
    )


def test_combine_partials_matches_joint_softmax(rng):
    """The stored-LSE merge primitive: attending two disjoint key sets
    separately and combining == attending their concatenation."""
    from gigapath_tpu.ops.flash_attention import (
        combine_partials,
        partial_attention,
    )

    B, Lq, Lk, H, D = 2, 8, 12, 3, 4
    q = jnp.asarray(rng.normal(size=(B, Lq, H, D)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, 2 * Lk, H, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, 2 * Lk, H, D)), jnp.float32)
    o_full, l_full = attention_with_lse(q, k, v)
    o_a, l_a = partial_attention(q, k[:, :Lk], v[:, :Lk])
    o_b, l_b = partial_attention(q, k[:, Lk:], v[:, Lk:])
    o_c, l_c = combine_partials(o_a.astype(jnp.float32), l_a, o_b, l_b)
    np.testing.assert_allclose(np.asarray(o_c), np.asarray(o_full), atol=1e-5)
    np.testing.assert_allclose(np.asarray(l_c), np.asarray(l_full), atol=1e-5)
