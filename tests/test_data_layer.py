"""Data layer: slide dataset (h5/pt), collate, splits, loaders, PCam, tiles.

Synthetic-fixture tests for the host-side pipeline the reference exercises
only through real PANDA/PCam downloads (``finetune/datasets/slide_datatset.py``,
``finetune/utils.py:63-206``, ``linear_probe/main.py:287-347``,
``gigapath/pipeline.py:21-52``).
"""

import io
import os
import zipfile

import numpy as np
import pandas as pd
import pytest

from gigapath_tpu.data.collate import next_power_of_two, pad_tensors, slide_collate_fn
from gigapath_tpu.data.loader import DataLoader, class_balance_weights, get_loader
from gigapath_tpu.data.pcam import EmbeddingDataset, Processor
from gigapath_tpu.data.slide_dataset import SlideDataset
from gigapath_tpu.data.splits import get_splits
from gigapath_tpu.data.tile_dataset import TileEncodingDataset, parse_tile_coords

D = 16


@pytest.fixture
def slide_fixture(tmp_path, rng):
    """5 slides as h5 (features+coords), a csv dataframe, a task config."""
    import h5py

    root = tmp_path / "h5_files"
    root.mkdir()
    rows = []
    for i in range(5):
        slide_id = f"slide_{i}.svs"
        n_tiles = 8 + 4 * i
        with h5py.File(root / f"slide_{i}.h5", "w") as f:
            f.create_dataset("features", data=rng.normal(size=(n_tiles, D)).astype(np.float32))
            f.create_dataset("coords", data=rng.integers(0, 5000, (n_tiles, 2)).astype(np.float32))
        rows.append(
            {"slide_id": slide_id, "pat_id": f"pat_{i % 3}", "label": ["neg", "pos"][i % 2]}
        )
    df = pd.DataFrame(rows)
    task_cfg = {
        "setting": "multi_class",
        "label_dict": {"neg": 0, "pos": 1},
        "max_tiles": 10,
        "shuffle_tiles": False,
    }
    return str(root), df, task_cfg


class TestSlideDataset:
    def test_h5_read_and_labels(self, slide_fixture):
        root, df, cfg = slide_fixture
        ds = SlideDataset(df, root, splits=df["pat_id"].tolist(), task_config=cfg)
        assert len(ds) == 5 and ds.n_classes == 2
        s = ds[0]
        assert s["imgs"].shape == (8, D)
        assert s["coords"].shape == (8, 2)
        assert s["labels"].shape == (1,)
        assert s["slide_id"] == "slide_0.svs"

    def test_max_tiles_truncation(self, slide_fixture):
        root, df, cfg = slide_fixture
        ds = SlideDataset(df, root, splits=df["pat_id"].tolist(), task_config=cfg)
        s = ds[4]  # 24 tiles > max 10
        assert s["imgs"].shape == (10, D)

    def test_missing_slides_filtered(self, slide_fixture):
        root, df, cfg = slide_fixture
        df2 = pd.concat(
            [df, pd.DataFrame([{"slide_id": "ghost.svs", "pat_id": "pat_0", "label": "neg"}])]
        )
        ds = SlideDataset(df2, root, splits=df2["pat_id"].tolist(), task_config=cfg)
        assert len(ds) == 5  # ghost dropped

    def test_split_filter(self, slide_fixture):
        root, df, cfg = slide_fixture
        ds = SlideDataset(df, root, splits=["pat_0"], task_config=cfg)
        assert len(ds) == 2  # slides 0 and 3

    def test_multi_label(self, slide_fixture, rng):
        root, df, cfg = slide_fixture
        df = df.copy()
        df["gene_a"] = [0, 1, 0, 1, 1]
        df["gene_b"] = [1, 1, 0, 0, 1]
        cfg = {
            "setting": "multi_label",
            "label_dict": {"gene_a": 0, "gene_b": 1},
            "max_tiles": 100,
        }
        ds = SlideDataset(df, root, splits=df["pat_id"].tolist(), task_config=cfg)
        s = ds[1]
        np.testing.assert_array_equal(s["labels"], [1, 1])

    def test_shuffle_tiles_seeded(self, slide_fixture):
        root, df, cfg = slide_fixture
        cfg = dict(cfg, shuffle_tiles=True)
        ds1 = SlideDataset(df, root, splits=df["pat_id"].tolist(), task_config=cfg, seed=1)
        ds2 = SlideDataset(df, root, splits=df["pat_id"].tolist(), task_config=cfg, seed=1)
        np.testing.assert_array_equal(ds1[0]["imgs"], ds2[0]["imgs"])

    def test_retry_skip_returns_none(self, slide_fixture, monkeypatch):
        root, df, cfg = slide_fixture
        ds = SlideDataset(df, root, splits=df["pat_id"].tolist(), task_config=cfg)
        monkeypatch.setattr(
            ds, "get_one_sample", lambda idx: (_ for _ in ()).throw(IOError("boom"))
        )
        assert ds[0] is None


class TestCollate:
    def test_pad_and_mask(self, rng):
        imgs = [rng.normal(size=(5, D)).astype(np.float32), rng.normal(size=(9, D)).astype(np.float32)]
        coords = [rng.normal(size=(5, 2)).astype(np.float32), rng.normal(size=(9, 2)).astype(np.float32)]
        p, c, m = pad_tensors(imgs, coords)
        assert p.shape == (2, 9, D) and c.shape == (2, 9, 2)
        assert m[0].sum() == 5 and m[1].sum() == 9
        np.testing.assert_array_equal(p[0, 5:], 0)

    def test_bucketed_padding(self, rng):
        imgs = [rng.normal(size=(21, D)).astype(np.float32)]
        coords = [rng.normal(size=(21, 2)).astype(np.float32)]
        p, _, m = pad_tensors(imgs, coords, bucket_fn=next_power_of_two)
        assert p.shape[1] == 32  # 21 -> 32
        assert m.sum() == 21

    def test_collate_drops_none(self, rng):
        sample = {
            "imgs": rng.normal(size=(4, D)).astype(np.float32),
            "coords": rng.normal(size=(4, 2)).astype(np.float32),
            "labels": np.asarray([1]),
            "slide_id": "s",
        }
        batch = slide_collate_fn([None, sample])
        assert batch["imgs"].shape[0] == 1
        assert slide_collate_fn([None, None]) is None

    def test_power_of_two(self):
        assert next_power_of_two(1) == 16  # floor
        assert next_power_of_two(16) == 16
        assert next_power_of_two(17) == 32
        assert next_power_of_two(1000) == 1024


class TestSplits:
    def test_create_and_fetch(self, tmp_path):
        df = pd.DataFrame(
            {"slide_id": [f"s{i}" for i in range(20)], "label": [i % 2 for i in range(20)]}
        )
        split_dir = str(tmp_path / "splits")
        tr, va, te = get_splits(df, split_dir=split_dir, fold=0)
        assert len(tr) + len(va) + len(te) == 20
        assert set(tr).isdisjoint(va) and set(tr).isdisjoint(te)
        # second call fetches the persisted files identically
        tr2, va2, te2 = get_splits(df, split_dir=split_dir, fold=0)
        assert tr == tr2 and va == va2 and te == te2

    def test_no_val_split(self, tmp_path):
        df = pd.DataFrame({"slide_id": [f"s{i}" for i in range(10)]})
        tr, va, te = get_splits(
            df, val_r=0.0, test_r=0.3, split_dir=str(tmp_path / "sp"), fold=1
        )
        assert va == [] and len(te) == 3


class TestLoader:
    def _dataset(self, rng, n=10):
        class DS:
            labels = np.asarray([[i % 2] for i in range(n)])

            def __len__(self):
                return n

            def __getitem__(self, i):
                return {
                    "imgs": rng.normal(size=(4 + i, D)).astype(np.float32),
                    "coords": np.zeros((4 + i, 2), np.float32),
                    "labels": self.labels[i],
                    "slide_id": f"s{i}",
                }

        return DS()

    def test_seeded_iteration_deterministic(self, rng):
        ds = self._dataset(rng)
        ids1 = [b["slide_id"][0] for b in DataLoader(ds, shuffle=True, seed=3)]
        ids2 = [b["slide_id"][0] for b in DataLoader(ds, shuffle=True, seed=3)]
        assert ids1 == ids2

    def test_weighted_sampling_balances(self, rng):
        # 9:1 imbalance; weighted sampler should draw the rare class often
        n = 100

        class DS:
            labels = np.asarray([[0]] * 90 + [[1]] * 10)

            def __len__(self):
                return n

            def __getitem__(self, i):
                return {
                    "imgs": np.zeros((2, D), np.float32),
                    "coords": np.zeros((2, 2), np.float32),
                    "labels": self.labels[i],
                    "slide_id": str(i),
                }

        ds = DS()
        weights = class_balance_weights(ds.labels)
        loader = DataLoader(ds, batch_size=1, weights=weights, seed=0)
        drawn = [int(b["labels"][0, 0]) for b in loader]
        rare = sum(drawn) / len(drawn)
        assert 0.3 < rare < 0.7  # ~0.5 expected vs 0.1 unweighted

    def test_get_loader_shapes(self, rng):
        ds = self._dataset(rng)
        tr, va, te = get_loader(ds, ds, ds, {"setting": "multi_class"}, batch_size=2)
        batch = next(iter(tr))
        assert batch["imgs"].ndim == 3 and batch["imgs"].shape[0] == 2
        assert next(iter(va))["imgs"].shape[0] == 1


class TestPCam:
    def test_zip_roundtrip(self, tmp_path, rng):
        import torch

        zpath = tmp_path / "embeds.zip"
        names, labels = [], []
        with zipfile.ZipFile(zpath, "w") as z:
            for split in ("train", "test"):
                for i in range(3):
                    name = f"{split}_{i}"
                    buf = io.BytesIO()
                    torch.save(torch.randn(8), buf)
                    z.writestr(f"embeds/{name}.pt", buf.getvalue())
                    names.append(name)
                    labels.append(["neg", "pos"][i % 2])
        csv = tmp_path / "ds.csv"
        pd.DataFrame(
            {
                "input": names,
                "label": labels,
                "split": ["train"] * 3 + ["test"] * 3,
            }
        ).to_csv(csv)

        ds = EmbeddingDataset(str(csv), str(zpath), split="train")
        assert len(ds) == 3
        embed, target = ds[0]
        assert embed.shape == (8,) and target in (0, 1)
        ds_z = EmbeddingDataset(str(csv), str(zpath), split="train", z_score=True)
        e, _ = ds_z[0]
        assert abs(e.mean()) < 1e-5 and abs(e.std() - 1) < 1e-4


class TestTileDataset:
    def test_coord_parse_and_load(self, tmp_path, rng):
        from PIL import Image

        p = tmp_path / "00123x_00456y.png"
        Image.fromarray(
            rng.integers(0, 255, (64, 64, 3)).astype(np.uint8)
        ).save(p)
        np.testing.assert_array_equal(parse_tile_coords(str(p)), [123, 456])

        from gigapath_tpu.data.transforms import preprocess_tile

        ds = TileEncodingDataset([str(p)], transform=preprocess_tile)
        sample = ds[0]
        assert sample["img"].shape == (224, 224, 3)
        np.testing.assert_array_equal(sample["coords"], [123, 456])


class TestDevicePrefetcher:
    def _loader(self, batches):
        class L:
            dataset = "ds"

            def __len__(self):
                return len(batches)

            def __iter__(self):
                return iter(batches)

        return L()

    def test_order_dtype_and_passthrough(self):
        import jax
        import jax.numpy as jnp

        from gigapath_tpu.data.loader import DevicePrefetcher

        batches = [
            {
                "imgs": np.full((1, 4, 8), i, np.float32),
                "pad_mask": np.ones((1, 4), bool),
                "slide_id": [f"s{i}"],
            }
            for i in range(5)
        ]
        out = list(DevicePrefetcher(self._loader(batches), depth=2))
        assert len(out) == 5
        for i, b in enumerate(out):
            assert isinstance(b["imgs"], jax.Array)
            assert b["imgs"].dtype == jnp.bfloat16  # halved transfer bytes
            assert float(b["imgs"][0, 0, 0]) == i  # order preserved
            assert b["pad_mask"].dtype == jnp.bool_
            assert b["slide_id"] == [f"s{i}"]  # host values untouched

    def test_none_batches_dropped(self):
        from gigapath_tpu.data.loader import DevicePrefetcher

        batches = [None, {"imgs": np.zeros((1, 2, 2), np.float32)}, None]
        out = list(DevicePrefetcher(self._loader(batches)))
        assert len(out) == 1

    def test_producer_error_reraises(self):
        import pytest

        from gigapath_tpu.data.loader import DevicePrefetcher

        def gen():
            yield {"imgs": np.zeros((1, 2, 2), np.float32)}
            raise RuntimeError("h5 went away")

        class L:
            def __iter__(self):
                return gen()

        pf = DevicePrefetcher(L())
        it = iter(pf)
        next(it)
        with pytest.raises(RuntimeError, match="h5 went away"):
            list(it)
