"""End-to-end request tracing (gigapath_tpu/obs/reqtrace.py).

Pinned: stable ``trace_id``/``span_id`` per request, Chrome-trace JSON
export (``ph: "X"`` complete events, µs clocks, one named track per
request, spans CONTAINED in their request), bounded memory with a
COUNTED overflow, export riding the runlog's closers, and the
zero-overhead-when-off twin (no clocks, no file, no event)."""

import json
import os

from gigapath_tpu.obs import NullRunLog, RunLog
from gigapath_tpu.obs.reqtrace import (
    NULL_REQUEST_TRACE,
    NullTraceCollector,
    RequestTrace,
    TraceCollector,
    get_tracer,
)


def _log(tmp_path, name="run.jsonl"):
    return RunLog(str(tmp_path / name), driver="t", echo=False)


class TestRequestTrace:
    def test_trace_ids_stable_and_unique(self, tmp_path):
        log = _log(tmp_path)
        try:
            col = TraceCollector(log)
            a = col.start("slide_a", now=1.0)
            b = col.start("slide_b", now=2.0)
            assert a.trace_id != b.trace_id
            assert a.trace_id.startswith(log.run_id)
            a.add_span("submit", 1.0, 1.1)
            a.add_span("queue", 1.1, 1.5)
            # every span_id carries the request's trace_id prefix
            assert [s.args["span_id"] for s in a.spans] == [
                f"{a.trace_id}.1", f"{a.trace_id}.2"
            ]
        finally:
            log.close()

    def test_t_last_chains_sibling_spans(self):
        tr = RequestTrace("t-1", 1, "s", t_start=5.0)
        assert tr.t_last == 5.0
        tr.add_span("submit", 5.0, 5.2)
        assert tr.t_last == 5.2

    def test_finish_first_close_wins_and_clamps(self):
        tr = RequestTrace("t-1", 1, "s", t_start=5.0)
        tr.finish(now=6.0, status="ok")
        tr.finish(now=9.0, status="error")  # late duplicate ignored
        assert tr.t_end == 6.0 and tr.status == "ok"
        sp = RequestTrace("t-2", 2, "s", 0.0)
        sp.add_span("x", 2.0, 1.0)  # clock jitter: clamped, not negative
        assert sp.spans[0].t1 == 2.0


class TestTraceCollector:
    def _traced(self, col):
        tr = col.start("slide_0", now=10.0, n_tiles=64)
        tr.add_span("submit", 10.0, 10.1, bucket=64)
        tr.add_span("queue", 10.1, 10.5, bucket=64)
        tr.add_span("dispatch", 10.5, 11.0, bucket=64)
        tr.add_span("forward", 10.6, 10.9, bucket=64)
        tr.finish(now=11.0)
        return tr

    def test_chrome_trace_export_shape_and_nesting(self, tmp_path):
        log = _log(tmp_path)
        col = TraceCollector(log)
        tr = self._traced(col)
        path = col.export()
        log.close()
        assert path == os.path.splitext(log.path)[0] + ".trace.json"
        doc = json.load(open(path))
        assert doc["displayTimeUnit"] == "ms"
        events = doc["traceEvents"]
        metas = [e for e in events if e["ph"] == "M"]
        assert metas and tr.trace_id in metas[0]["args"]["name"]
        xs = [e for e in events if e["ph"] == "X"]
        root = [e for e in xs if e["name"] == "request"][0]
        assert root["args"]["trace_id"] == tr.trace_id
        # µs clocks: the request lasted 1.0 s
        assert root["dur"] == 1e6
        lo, hi = root["ts"], root["ts"] + root["dur"]
        for e in xs:
            assert e["tid"] == tr.tid
            assert lo <= e["ts"] and e["ts"] + e["dur"] <= hi, (
                f"span {e['name']} escapes its request"
            )
            assert e["args"]["trace_id"] == tr.trace_id
        assert {e["name"] for e in xs} == {
            "request", "submit", "queue", "dispatch", "forward"
        }

    def test_export_event_once_and_rewrite_idempotent(self, tmp_path):
        log = _log(tmp_path)
        col = TraceCollector(log)
        self._traced(col)
        col.export()
        self._traced(col)
        col.export()  # rewrites the file, emits NO second trace event
        log.close()
        events = [json.loads(line) for line in open(log.path)]
        trace_events = [ev for ev in events if ev["kind"] == "trace"]
        assert len(trace_events) == 1
        doc = json.load(open(col.path))
        roots = [e for e in doc["traceEvents"]
                 if e["ph"] == "X" and e["name"] == "request"]
        assert len(roots) == 2  # the rewrite carries both requests

    def test_empty_collector_exports_nothing(self, tmp_path):
        log = _log(tmp_path)
        col = TraceCollector(log)
        assert col.export() is None
        log.close()
        assert not os.path.exists(col.path)
        events = [json.loads(line) for line in open(log.path)]
        assert not [ev for ev in events if ev["kind"] == "trace"]

    def test_max_traces_cap_counts_dropped(self, tmp_path):
        log = _log(tmp_path)
        col = TraceCollector(log, max_traces=2)
        a = col.start("s0")
        b = col.start("s1")
        c = col.start("s2")  # past the cap: the shared null trace
        assert c is NULL_REQUEST_TRACE and a is not b
        for tr in (a, b):
            tr.add_span("submit", tr.t_start, tr.t_start + 0.1)
            tr.finish()
        col.export()
        log.close()
        trace_ev = [json.loads(line) for line in open(log.path)
                    if '"trace"' in line][-1]
        assert trace_ev["traces"] == 2 and trace_ev["dropped"] == 1


class TestGetTracer:
    def test_null_runlog_yields_null_collector(self):
        col = get_tracer(NullRunLog())
        assert isinstance(col, NullTraceCollector)
        assert not isinstance(col, TraceCollector)
        tr = col.start("s")
        assert tr is NULL_REQUEST_TRACE
        tr.add_span("x", 0, 1)
        tr.finish()
        assert col.export() is None and col.path is None

    def test_attach_once_and_export_rides_run_end(self, tmp_path):
        log = _log(tmp_path)
        col = get_tracer(log)
        assert isinstance(col, TraceCollector)
        assert get_tracer(log) is col
        tr = col.start("slide", now=1.0)
        tr.add_span("submit", 1.0, 1.2)
        tr.finish(now=1.2)
        log.run_end(status="ok")  # closers run the export
        assert os.path.exists(col.path)
        events = [json.loads(line) for line in open(log.path)]
        assert [ev for ev in events if ev["kind"] == "trace"]

    def test_max_traces_env_read_once(self, tmp_path, monkeypatch):
        monkeypatch.setenv("GIGAPATH_TRACE_MAX", "1")
        log = _log(tmp_path)
        try:
            col = get_tracer(log)
            assert col.max_traces == 1
            col.start("a")
            assert col.start("b") is NULL_REQUEST_TRACE
        finally:
            log.close()
