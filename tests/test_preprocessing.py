"""WSI preprocessing: Otsu, ROI, tiling pipeline, ledgers, resume, merge.

Synthetic-slide end-to-end tests (the reference has none): a white slide
with a dark tissue blob -> ROI crop covers the blob, PNG tiles +
``dataset.csv`` ledger written, failed_tiles.csv empty, resume skips
re-processing, merged csv aggregates slides.
"""

import numpy as np
import pandas as pd
import pytest
from PIL import Image

from gigapath_tpu.preprocessing.create_tiles_dataset import (
    check_empty_tiles,
    generate_tiles,
    get_tile_descriptor,
    get_tile_id,
    is_already_processed,
    main as preprocess_main,
    merge_dataset_csv_files,
    process_slide,
    select_tiles,
)
from gigapath_tpu.preprocessing.foreground_segmentation import (
    ImageSlideReader,
    LoadROId,
    otsu_threshold,
    segment_foreground,
)


def _synthetic_slide(size=256, blob=None, seed=0):
    """White background + dark noisy tissue blob, HWC uint8."""
    rng = np.random.default_rng(seed)
    arr = np.full((size, size, 3), 245, np.uint8)
    if blob is None:
        blob = ((size // 4, 3 * size // 4), (3 * size // 8, 7 * size // 8))
    (y0, y1), (x0, x1) = blob
    arr[y0:y1, x0:x1] = rng.integers(30, 120, (y1 - y0, x1 - x0, 3))
    return arr


class TestSegmentation:
    def test_otsu_separates_bimodal(self, rng):
        values = np.concatenate([rng.normal(40, 5, 500), rng.normal(220, 5, 500)])
        th = otsu_threshold(values)
        assert 60 < th < 200

    def test_foreground_is_dark_tissue(self):
        arr = _synthetic_slide()
        chw = np.moveaxis(arr, -1, 0)
        mask, th = segment_foreground(chw)
        assert mask.shape == chw.shape[1:]
        assert mask[128, 128]  # inside blob
        assert not mask[10, 10]  # background
        # fixed threshold respected
        mask2, th2 = segment_foreground(chw, threshold=150.0)
        assert th2 == 150.0

    def test_image_slide_reader_pyramid(self):
        arr = _synthetic_slide(128)
        reader = ImageSlideReader(arr, n_levels=3)
        assert reader.level_count == 3
        assert reader.level_dimensions[0] == (128, 128)
        assert reader.level_dimensions[2] == (32, 32)
        region = reader.read_region((8, 16), 0, (32, 32))
        np.testing.assert_array_equal(
            region, np.moveaxis(arr[8:40, 16:48], -1, 0)
        )

    def test_load_roid_crops_to_blob(self, tmp_path):
        arr = _synthetic_slide()
        path = tmp_path / "slide.png"
        Image.fromarray(arr).save(path)
        loader = LoadROId(level=0, margin=0)
        out = loader({"image": str(path), "slide_id": "s1"})
        img = out["image"]
        # ROI is roughly blob-sized (pyramid rounding allows slack)
        assert img.shape[0] == 3
        assert img.shape[1] <= 160 and img.shape[2] <= 160
        assert out["scale"] == 1.0
        y, x = out["origin"]
        assert 48 <= y <= 72 and 80 <= x <= 104


class TestTileSelection:
    def test_select_tiles_threshold(self):
        mask = np.zeros((4, 8, 8), bool)
        mask[0] = True  # fully occupied
        mask[1, :4] = True  # half
        selected, occ = select_tiles(mask, 0.4)
        np.testing.assert_array_equal(selected, [True, True, False, False])
        assert occ[0] == 1.0

    def test_select_tiles_invalid_threshold(self):
        with pytest.raises(ValueError):
            select_tiles(np.zeros((1, 2, 2), bool), 1.5)

    def test_descriptors(self):
        assert get_tile_descriptor((123, 456)) == "00123x_00456y"
        assert get_tile_id("s1", (1, 2)) == "s1.00001x_00002y"

    def test_check_empty_tiles(self, rng):
        tiles = rng.integers(0, 255, (3, 3, 16, 16)).astype(np.float32)
        tiles[1] = 128.0  # zero variance
        tiles[2] = 0.0  # extreme values
        empty = check_empty_tiles(tiles)
        np.testing.assert_array_equal(empty, [False, True, True])

    def test_generate_tiles_discards_background(self):
        arr = _synthetic_slide(128, blob=((0, 64), (0, 64)))
        chw = np.moveaxis(arr, -1, 0)
        tiles, locations, occ, n_discarded = generate_tiles(
            chw, tile_size=64, foreground_threshold=150.0, occupancy_threshold=0.5
        )
        assert tiles.shape[0] == 1  # only the blob tile survives
        np.testing.assert_array_equal(locations[0], [0, 0])
        assert n_discarded == 3


class TestProcessSlide:
    def _sample(self, tmp_path, slide_id="slide_a", seed=0):
        arr = _synthetic_slide(256, seed=seed)
        path = tmp_path / f"{slide_id}.png"
        Image.fromarray(arr).save(path)
        return {
            "slide_id": slide_id,
            "image": str(path),
            "label": 1,
            "metadata": {"provider": "synthetic"},
        }

    def test_end_to_end_single_slide(self, tmp_path):
        sample = self._sample(tmp_path)
        out_dir = tmp_path / "out"
        tiles_dir = process_slide(
            sample,
            level=0,
            margin=0,
            tile_size=64,
            foreground_threshold=None,
            occupancy_threshold=0.1,
            output_dir=out_dir,
            thumbnail_dir=out_dir / "thumbnails",
        )
        df = pd.read_csv(tiles_dir / "dataset.csv")
        assert len(df) > 0
        assert set(df.columns) >= {
            "slide_id", "tile_id", "image", "tile_x", "tile_y", "occupancy",
            "slide_provider",
        }
        # the reference pipeline's invariant (pipeline.py:96-101):
        # dataset non-empty, failed_tiles empty
        failed = pd.read_csv(tiles_dir / "failed_tiles.csv")
        assert len(failed) == 0
        # every listed PNG exists and parses back to its coordinates
        from gigapath_tpu.data.tile_dataset import parse_tile_coords

        for _, row in df.iterrows():
            p = out_dir / row["image"]
            assert p.exists()
            x, y = parse_tile_coords(str(p))
            assert x == row["tile_x"] and y == row["tile_y"]
        # thumbnails + overlay written
        assert (out_dir / "thumbnails" / "slide_a.png_original.png").exists()
        assert (out_dir / "thumbnails" / "slide_a.png_roi_tiles.png").exists()

    def test_resume_skips_processed(self, tmp_path):
        sample = self._sample(tmp_path)
        out_dir = tmp_path / "out"
        kwargs = dict(
            level=0, margin=0, tile_size=64, foreground_threshold=None,
            occupancy_threshold=0.1, output_dir=out_dir,
            thumbnail_dir=out_dir / "thumbnails",
        )
        tiles_dir = process_slide(sample, **kwargs)
        assert is_already_processed(tiles_dir)
        mtime = (tiles_dir / "dataset.csv").stat().st_mtime_ns
        process_slide(sample, **kwargs)  # resume: no rewrite
        assert (tiles_dir / "dataset.csv").stat().st_mtime_ns == mtime

    def test_main_merges_csvs(self, tmp_path):
        samples = [
            self._sample(tmp_path, "slide_a", 0),
            self._sample(tmp_path, "slide_b", 1),
        ]
        out_dir = tmp_path / "dataset"
        preprocess_main(
            samples,
            out_dir,
            level=0,
            tile_size=64,
            margin=0,
            foreground_threshold=None,
            occupancy_threshold=0.1,
        )
        merged = pd.read_csv(out_dir / "dataset.csv")
        assert set(merged["slide_id"]) == {"slide_a", "slide_b"}
        per_slide = [
            len(pd.read_csv(out_dir / s / "dataset.csv"))
            for s in ("slide_a", "slide_b")
        ]
        assert len(merged) == sum(per_slide)
