"""gigarace wiring: lock discipline holds at HEAD, and the pass works.

Mirrors tests/test_gigalint.py's contract pair for the four
lock-discipline rules (GL018 deadlock cycles / self-deadlock, GL019
guarded-field races, GL020 signal-path blocking, GL021 blocking under
lock):

1. The library tree is CLEAN — zero unwaived findings — so every rule
   runs on every ``pytest -q`` and every ``scripts/lint.sh``.
2. The seeded fixture tree under tools/gigarace/selftest/fixture/
   fires EXACTLY its seeded violations (counts and line numbers) while
   the negative controls stay silent — the rules neither go blind nor
   over-fire.

Plus the model's supporting surfaces: the lock inventory, the static
order graph, the annotation mechanisms, and the --validate consumer's
static-vs-runtime drift check.
"""

import json
import os
import subprocess
import sys

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURE = "tools/gigarace/selftest/fixture"

sys.path.insert(0, REPO_ROOT)

from tools.gigalint.cli import run_lint  # noqa: E402
from tools.gigarace.cli import (  # noqa: E402
    graph_dict,
    load_model,
    render_inventory,
    validate_traces,
)
from tools.gigarace.rules import RACE_RULES  # noqa: E402

RACE_SELECT = sorted(RACE_RULES)


def _fixture_findings(path):
    result = run_lint(
        [f"{FIXTURE}/{path}"], root=REPO_ROOT,
        waiver_file=None, select=RACE_SELECT,
    )
    assert result.errors == []
    return result.findings


# ---------------------------------------------------------------------------
# contract 1: the library is clean
# ---------------------------------------------------------------------------

def test_library_is_clean():
    result = run_lint(
        ["gigapath_tpu", "scripts", "tests"], root=REPO_ROOT,
        select=RACE_SELECT,
    )
    assert result.errors == []
    assert result.findings == [], "\n".join(f.text() for f in result.findings)


# ---------------------------------------------------------------------------
# contract 2: the seeded fixtures fire exactly as seeded
# ---------------------------------------------------------------------------

def test_deadlock_fixture_fires_exactly():
    findings = _fixture_findings("deadlock.py")
    got = sorted((f.rule, f.lineno) for f in findings)
    assert got == [("GL018", 21), ("GL018", 37)], (
        "\n".join(f.text() for f in findings)
    )
    # one cycle finding, one self-deadlock finding
    texts = "\n".join(f.text() for f in findings)
    assert "cycle" in texts
    assert "already held" in texts or "re-acquir" in texts or \
        "self-deadlock" in texts


def test_races_fixture_fires_exactly():
    findings = _fixture_findings("races.py")
    got = sorted((f.rule, f.lineno) for f in findings)
    assert got == [("GL019", 26), ("GL019", 29), ("GL019", 29)], (
        "\n".join(f.text() for f in findings)
    )


def test_sigpath_fixture_fires_exactly():
    findings = _fixture_findings("sigpath.py")
    got = sorted((f.rule, f.lineno) for f in findings)
    assert got == [("GL020", 33), ("GL020", 36), ("GL020", 57)], (
        "\n".join(f.text() for f in findings)
    )
    texts = "\n".join(f.text() for f in findings)
    assert "print" in texts          # the buffered-stdio arm
    assert "_from_signal" in texts   # the prescribed discipline


def test_joinwait_fixture_fires_exactly():
    findings = _fixture_findings("joinwait.py")
    got = sorted((f.rule, f.lineno) for f in findings)
    assert got == [("GL021", 22), ("GL021", 26), ("GL021", 43)], (
        "\n".join(f.text() for f in findings)
    )


def test_fixture_negative_controls_stay_silent():
    result = run_lint(
        [FIXTURE], root=REPO_ROOT, waiver_file=None, select=RACE_SELECT,
    )
    for f in result.findings:
        assert "negative_control" not in f.symbol, f.text()
        assert "OrderedPair" not in f.symbol, f.text()


def test_gigalint_fixture_tree_stays_quiet_for_race_rules():
    """The race rules must not over-fire on gigalint's own (unrelated)
    seeded-violation tree — rule isolation, both directions."""
    result = run_lint(
        ["tools/gigalint/selftest/fixture"], root=REPO_ROOT,
        waiver_file=None, select=RACE_SELECT,
    )
    assert result.findings == [], "\n".join(f.text() for f in result.findings)


# ---------------------------------------------------------------------------
# the model's supporting surfaces
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def library_model():
    model, errors = load_model(["gigapath_tpu"], root=REPO_ROOT)
    assert errors == []
    return model


def test_inventory_covers_the_known_lock_set(library_model):
    table = render_inventory(library_model)
    for needle in (
        "gigapath_tpu.serve.service.SlideService._lock",
        "gigapath_tpu.serve.queue.RequestQueue._cond",
        "gigapath_tpu.serve.cache.EmbeddingCache._lock",
        "gigapath_tpu.obs.runlog.RunLog._lock",
        "gigapath_tpu.obs.metrics.MetricsRegistry._lock",
        "gigapath_tpu.obs.anomaly.AnomalyEngine._lock",
        "gigapath_tpu.dist.boundary.MemoryChannel._cond",
    ):
        assert needle in table, f"inventory lost {needle}"
    assert table.splitlines()[0] == (
        "| lock | kind | declared at | guarded fields |")
    # the guarded-fields column carries the GL019 resolution
    assert "`SlideService._pending`" in table
    assert "`MemoryChannel._queue`" in table


def test_inventory_matches_readme(library_model):
    """The README's "Concurrency discipline" table is generated by
    --inventory; regen-and-paste, never hand-edit. This pins the two
    against drift."""
    table = render_inventory(library_model)
    with open(os.path.join(REPO_ROOT, "README.md"), encoding="utf-8") as fh:
        readme = fh.read()
    assert table in readme, (
        "README lock table is stale — regenerate with "
        "`python -m tools.gigarace --inventory` and paste it into the "
        "'Concurrency discipline' section"
    )


def test_graph_is_acyclic_at_head(library_model):
    g = graph_dict(library_model)
    assert g["cycles"] == []
    assert g["self_deadlocks"] == []
    edges = {(e["src"], e["dst"]) for e in g["edges"]}
    # the serving dispatch loop's nesting is the load-bearing order
    assert ("gigapath_tpu.serve.service.SlideService._lock",
            "gigapath_tpu.obs.metrics.MetricsRegistry._lock") in edges


def test_validate_accepts_covered_trace(library_model, tmp_path):
    g = graph_dict(library_model)
    edge = g["edges"][0]
    trace = tmp_path / "run.jsonl"
    trace.write_text(json.dumps({
        "kind": "locktrace",
        "locks": [edge["src"], edge["dst"]],
        "edges": [[edge["src"], edge["dst"]]],
        "violations": [],
    }) + "\n")
    problems, stats = validate_traces(library_model, [str(trace)])
    assert problems == []
    assert stats["payloads"] == 1
    assert stats["covered_edges"] == 1 == stats["observed_edges"]


def test_validate_flags_drift(library_model, tmp_path):
    src = "gigapath_tpu.serve.service.SlideService._lock"
    dst = "gigapath_tpu.obs.runlog.RunLog._lock"
    trace = tmp_path / "run.jsonl"
    trace.write_text("\n".join([
        # unknown lock name: runtime/static naming drift
        json.dumps({"kind": "locktrace",
                    "locks": ["no.such.Lock"], "edges": []}),
        # observed order with no static edge (reversed nesting)
        json.dumps({"kind": "locktrace", "locks": [src, dst],
                    "edges": [[dst, src]]}),
        # a runtime violation is a problem verbatim
        json.dumps({"kind": "locktrace", "locks": [], "edges": [],
                    "violations": ["lock order inversion: x vs y"]}),
        # non-locktrace runlog records are skipped, not misparsed
        json.dumps({"kind": "step", "t": 0.0}),
    ]) + "\n")
    problems, stats = validate_traces(library_model, [str(trace)])
    assert stats["payloads"] == 3
    assert any("no.such.Lock" in p for p in problems)
    assert any("no static edge" in p for p in problems)
    assert any("inversion" in p for p in problems)


def test_validate_empty_file_is_a_problem(library_model, tmp_path):
    trace = tmp_path / "empty.jsonl"
    trace.write_text("")
    problems, stats = validate_traces(library_model, [str(trace)])
    assert stats["payloads"] == 0
    assert any("no locktrace payloads" in p for p in problems)


# ---------------------------------------------------------------------------
# annotation mechanisms
# ---------------------------------------------------------------------------

def _lint_snippet(tmp_path, source, select):
    mod = tmp_path / "gigapath_tpu" / "snippet.py"
    mod.parent.mkdir(parents=True, exist_ok=True)
    mod.write_text(source)
    result = run_lint(
        ["gigapath_tpu/snippet.py"], root=str(tmp_path),
        waiver_file=None, select=select,
    )
    assert result.errors == []
    return result.findings


def test_guarded_by_annotation_declares_discipline(tmp_path):
    findings = _lint_snippet(tmp_path, (
        "import threading\n"
        "\n"
        "class Box:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._n = 0  # gigarace: guarded-by _lock\n"
        "\n"
        "    def peek(self):\n"
        "        return self._n\n"
    ), ["GL019"])
    assert [(f.rule, f.lineno) for f in findings] == [("GL019", 9)]


def test_unguarded_annotation_opts_out(tmp_path):
    findings = _lint_snippet(tmp_path, (
        "import threading\n"
        "\n"
        "class Box:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._n = 0  # gigarace: unguarded -- monotonic flag\n"
        "\n"
        "    def bump(self):\n"
        "        with self._lock:\n"
        "            self._n += 1\n"
        "\n"
        "    def peek(self):\n"
        "        return self._n\n"
    ), ["GL019"])
    assert findings == []


def test_calls_hint_feeds_the_order_graph(tmp_path):
    """# gigarace: calls closes the dynamic-dispatch blind spot: the
    hinted callee's acquisition shows up as a static edge under the
    caller's held lock."""
    mod = tmp_path / "gigapath_tpu" / "obsish.py"
    mod.parent.mkdir(parents=True, exist_ok=True)
    mod.write_text(
        "import threading\n"
        "\n"
        "class Sink:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "\n"
        "    def on_event(self, ev):\n"
        "        with self._lock:\n"
        "            return ev\n"
        "\n"
        "class Hub:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._observers = []\n"
        "\n"
        "    def emit(self, ev):\n"
        "        with self._lock:\n"
        "            for obs in self._observers:\n"
        "                obs(ev)  # gigarace: calls Sink.on_event\n"
    )
    model, errors = load_model(["gigapath_tpu"], root=str(tmp_path))
    assert errors == []
    edges = set(model.edges)
    assert ("gigapath_tpu.obsish.Hub._lock",
            "gigapath_tpu.obsish.Sink._lock") in edges


# ---------------------------------------------------------------------------
# CLI surfaces
# ---------------------------------------------------------------------------

def test_cli_rule_mode_exit_codes():
    proc = subprocess.run(
        [sys.executable, "-m", "tools.gigarace", "gigapath_tpu"],
        capture_output=True, text=True, cwd=REPO_ROOT, timeout=600,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    proc = subprocess.run(
        [sys.executable, "-m", "tools.gigarace", "--no-waivers", FIXTURE],
        capture_output=True, text=True, cwd=REPO_ROOT, timeout=600,
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr


def test_cli_graph_json_shape():
    proc = subprocess.run(
        [sys.executable, "-m", "tools.gigarace", "--graph", "gigapath_tpu"],
        capture_output=True, text=True, cwd=REPO_ROOT, timeout=600,
    )
    assert proc.returncode == 0, proc.stderr
    g = json.loads(proc.stdout)
    assert g["version"] == 1
    assert g["cycles"] == [] and g["self_deadlocks"] == []
    assert g["locks"] and g["edges"]
