"""Decoder / EncoderDecoder / RetNet / MultiScaleRetention / BERT init.

Covers the reference components the gigapath pipeline never exercises
(SURVEY §2.2/§2.3): causal decoding with a flax KV cache, cross-attention,
retention in its three equivalent modes — including a *golden parity* test
injecting identical weights into the reference torch MultiScaleRetention —
and the trunc-normal BERT init transform.
"""

import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gigapath_tpu.architecture.config import (
    DecoderConfig,
    EncoderDecoderConfig,
    RetNetConfig,
)
from gigapath_tpu.architecture.decoder import Decoder
from gigapath_tpu.architecture.encoder_decoder import EncoderDecoder
from gigapath_tpu.architecture.retnet import RetNetDecoder
from gigapath_tpu.ops.multiscale_retention import (
    MultiScaleRetention,
    retnet_rel_pos,
)

VOCAB = 50


def _decoder_cfg(**kw):
    base = dict(
        decoder_embed_dim=32,
        decoder_attention_heads=4,
        decoder_ffn_embed_dim=64,
        decoder_layers=2,
        vocab_size=VOCAB,
        dropout=0.0,
        drop_path_rate=0.0,
    )
    return DecoderConfig(**{**base, **kw})


class TestDecoder:
    def test_forward_shapes(self, rng):
        cfg = _decoder_cfg()
        dec = Decoder(cfg)
        tokens = jnp.asarray(rng.integers(0, VOCAB, (2, 9)), jnp.int32)
        params = dec.init(jax.random.PRNGKey(0), tokens)["params"]
        out = dec.apply({"params": params}, tokens)
        assert out["decoder_out"].shape == (2, 9, VOCAB)

    def test_causality(self, rng):
        """Changing a future token must not change past logits."""
        cfg = _decoder_cfg()
        dec = Decoder(cfg)
        tokens = jnp.asarray(rng.integers(0, VOCAB, (1, 8)), jnp.int32)
        params = dec.init(jax.random.PRNGKey(0), tokens)["params"]
        out1 = dec.apply({"params": params}, tokens)["decoder_out"]
        tokens2 = tokens.at[0, 5].set((tokens[0, 5] + 1) % VOCAB)
        out2 = dec.apply({"params": params}, tokens2)["decoder_out"]
        np.testing.assert_allclose(
            np.asarray(out1[0, :5]), np.asarray(out2[0, :5]), atol=1e-5
        )
        assert not np.allclose(np.asarray(out1[0, 5:]), np.asarray(out2[0, 5:]))

    def test_incremental_decode_matches_full(self, rng):
        """Stepwise KV-cache decoding == full causal forward."""
        cfg = _decoder_cfg()
        dec = Decoder(cfg)
        T = 7
        tokens = jnp.asarray(rng.integers(0, VOCAB, (2, T)), jnp.int32)
        variables = dec.init(jax.random.PRNGKey(0), tokens, decode=True)
        params, cache = variables["params"], variables["cache"]
        full = dec.apply({"params": params}, tokens)["decoder_out"]

        step_outs = []
        for t in range(T):
            out, mods = dec.apply(
                {"params": params, "cache": cache},
                tokens[:, t : t + 1],
                decode=True,
                mutable=["cache"],
            )
            cache = mods["cache"]
            step_outs.append(out["decoder_out"][:, 0])
        stepped = jnp.stack(step_outs, axis=1)
        np.testing.assert_allclose(
            np.asarray(full), np.asarray(stepped), atol=2e-4
        )

    def test_chunked_prefill_decode_matches_full(self, rng):
        """Multi-token decode chunks stay causal (per-query cache bias)."""
        cfg = _decoder_cfg()
        dec = Decoder(cfg)
        T = 8
        tokens = jnp.asarray(rng.integers(0, VOCAB, (1, T)), jnp.int32)
        variables = dec.init(jax.random.PRNGKey(0), tokens, decode=True)
        params, cache = variables["params"], variables["cache"]
        full = dec.apply({"params": params}, tokens)["decoder_out"]
        chunks = []
        for lo, hi in ((0, 3), (3, 5), (5, 8)):
            out, mods = dec.apply(
                {"params": params, "cache": cache},
                tokens[:, lo:hi],
                decode=True,
                mutable=["cache"],
            )
            cache = mods["cache"]
            chunks.append(out["decoder_out"])
        stepped = jnp.concatenate(chunks, axis=1)
        np.testing.assert_allclose(np.asarray(full), np.asarray(stepped), atol=2e-4)

    def test_shared_embedding_output(self, rng):
        cfg = _decoder_cfg(share_decoder_input_output_embed=True)
        dec = Decoder(cfg)
        tokens = jnp.asarray(rng.integers(0, VOCAB, (1, 5)), jnp.int32)
        params = dec.init(jax.random.PRNGKey(0), tokens)["params"]
        assert "output_projection" not in params
        out = dec.apply({"params": params}, tokens)["decoder_out"]
        assert out.shape == (1, 5, VOCAB)

    def test_moe_decoder_layer(self, rng):
        cfg = _decoder_cfg(moe_freq=2, moe_expert_count=4, moe_top1_expert=True)
        dec = Decoder(cfg)
        tokens = jnp.asarray(rng.integers(0, VOCAB, (2, 8)), jnp.int32)
        params = dec.init(jax.random.PRNGKey(0), tokens)["params"]
        out, mods = dec.apply({"params": params}, tokens, mutable=["intermediates"])
        assert any(l is not None for l in out["l_aux"])
        assert "moe_l_aux" in mods["intermediates"]

    def test_remat_matches_plain(self, rng):
        tokens = jnp.asarray(rng.integers(0, VOCAB, (1, 6)), jnp.int32)
        outs = []
        for ckpt in (False, True):
            cfg = _decoder_cfg(checkpoint_activations=ckpt)
            dec = Decoder(cfg)
            params = dec.init(jax.random.PRNGKey(0), tokens)["params"]
            outs.append(dec.apply({"params": params}, tokens)["decoder_out"])
        np.testing.assert_allclose(np.asarray(outs[0]), np.asarray(outs[1]), atol=1e-6)


class TestEncoderDecoder:
    def test_seq2seq_forward(self, rng):
        cfg = EncoderDecoderConfig(
            encoder_embed_dim=32,
            encoder_attention_heads=4,
            encoder_ffn_embed_dim=64,
            encoder_layers=2,
            decoder_embed_dim=32,
            decoder_attention_heads=4,
            decoder_ffn_embed_dim=64,
            decoder_layers=2,
            vocab_size=VOCAB,
            dropout=0.0,
            drop_path_rate=0.0,
        )
        model = EncoderDecoder(cfg)
        src = jnp.asarray(rng.integers(0, VOCAB, (2, 10)), jnp.int32)
        tgt = jnp.asarray(rng.integers(0, VOCAB, (2, 6)), jnp.int32)
        params = model.init(jax.random.PRNGKey(0), src, tgt)["params"]
        out = model.apply({"params": params}, src, tgt)
        assert out["decoder_out"].shape == (2, 6, VOCAB)
        # cross-attention is live: changing the source changes the output
        src2 = src.at[0, 0].set((src[0, 0] + 1) % VOCAB)
        out2 = model.apply({"params": params}, src2, tgt)
        assert not np.allclose(
            np.asarray(out["decoder_out"][0]), np.asarray(out2["decoder_out"][0])
        )

    def test_moe_layers_use_side_specific_dims(self, rng):
        """Encoder MoE experts get encoder dims, decoder MoE decoder dims."""
        cfg = EncoderDecoderConfig(
            encoder_embed_dim=32,
            encoder_attention_heads=4,
            encoder_ffn_embed_dim=48,
            encoder_layers=2,
            decoder_embed_dim=16,
            decoder_attention_heads=2,
            decoder_ffn_embed_dim=24,
            decoder_layers=2,
            vocab_size=VOCAB,
            dropout=0.0,
            drop_path_rate=0.0,
            moe_freq=2,
            moe_expert_count=2,
            moe_top1_expert=True,
        )
        model = EncoderDecoder(cfg)
        src = jnp.asarray(rng.integers(0, VOCAB, (1, 6)), jnp.int32)
        tgt = jnp.asarray(rng.integers(0, VOCAB, (1, 4)), jnp.int32)
        params = model.init(jax.random.PRNGKey(0), src, tgt)["params"]
        enc_k = params["encoder"]["layers_1"]["moe_layer"]["experts"]["fc1"]["kernel"]
        dec_k = params["decoder"]["layers_1"]["moe_layer"]["experts"]["fc1"]["kernel"]
        assert enc_k.shape == (2, 32, 48)
        assert dec_k.shape == (2, 16, 24)


def _msr(num_heads=4, embed_dim=32, value_dim=64):
    return MultiScaleRetention(
        embed_dim=embed_dim, value_dim=value_dim, num_heads=num_heads
    )


class TestMultiScaleRetention:
    def test_parallel_shape(self, rng):
        msr = _msr()
        x = jnp.asarray(rng.normal(size=(2, 8, 32)), jnp.float32)
        rel = retnet_rel_pos(8, 32, 4)
        params = msr.init(jax.random.PRNGKey(0), x, rel)["params"]
        out = msr.apply({"params": params}, x, rel)
        assert out.shape == (2, 8, 32)

    def test_parallel_matches_chunkwise(self, rng):
        msr = _msr()
        T, C = 16, 4
        x = jnp.asarray(rng.normal(size=(2, T, 32)), jnp.float32)
        rel_par = retnet_rel_pos(T, 32, 4)
        rel_chunk = retnet_rel_pos(
            T, 32, 4, chunkwise_recurrent=True, recurrent_chunk_size=C
        )
        params = msr.init(jax.random.PRNGKey(0), x, rel_par)["params"]
        out_par = msr.apply({"params": params}, x, rel_par)
        out_chunk = msr.apply(
            {"params": params}, x, rel_chunk, chunkwise_recurrent=True
        )
        # group-norm cancels most mode-specific scaling, but the clamp()ed
        # detached denominators leave a small gap; the reference torch module
        # shows the same max-abs ~7.5e-3 between its own two modes
        np.testing.assert_allclose(
            np.asarray(out_par), np.asarray(out_chunk), atol=2e-2
        )

    def test_parallel_matches_recurrent(self, rng):
        msr = _msr()
        T = 6
        x = jnp.asarray(rng.normal(size=(1, T, 32)), jnp.float32)
        rel_par = retnet_rel_pos(T, 32, 4)
        variables = msr.init(
            jax.random.PRNGKey(0), x[:, :1], retnet_rel_pos(1, 32, 4, activate_recurrent=True), decode=True
        )
        params, cache = variables["params"], variables["cache"]
        out_par = msr.apply({"params": params}, x, rel_par)

        outs = []
        for t in range(T):
            rel_t = retnet_rel_pos(t + 1, 32, 4, activate_recurrent=True)
            out_t, mods = msr.apply(
                {"params": params, "cache": cache},
                x[:, t : t + 1],
                rel_t,
                decode=True,
                mutable=["cache"],
            )
            cache = mods["cache"]
            outs.append(out_t[:, 0])
        out_rec = jnp.stack(outs, axis=1)
        # same clamp-induced gap as the chunkwise comparison above
        np.testing.assert_allclose(
            np.asarray(out_par), np.asarray(out_rec), atol=2e-2
        )

    def test_golden_parity_with_reference_torch(self, rng):
        """Inject identical weights into the reference torch module and
        compare outputs (parallel mode)."""
        torch = pytest.importorskip("torch")
        sys.path.insert(0, "/root/reference/gigapath")
        try:
            from torchscale.component.multiscale_retention import (
                MultiScaleRetention as RefMSR,
            )
        except ImportError:
            # the reference torchscale checkout is an external artifact
            # (not part of this repo); containers without it skip the
            # golden comparison instead of failing collection-adjacent
            pytest.skip("reference torchscale checkout not available")
        finally:
            sys.path.pop(0)

        class Args:
            multiway = False
            layernorm_eps = 1e-6

        E, V, H, T = 32, 64, 4, 8
        ref = RefMSR(Args(), E, V, H)
        msr = _msr(num_heads=H, embed_dim=E, value_dim=V)
        x_np = rng.normal(size=(2, T, E)).astype(np.float32)
        rel = retnet_rel_pos(T, E, H)
        params = msr.init(jax.random.PRNGKey(0), jnp.asarray(x_np), rel)["params"]

        # copy flax kernels into the torch module (torch Linear weight = W.T)
        with torch.no_grad():
            for name in ("q_proj", "k_proj", "v_proj", "g_proj", "out_proj"):
                w = np.asarray(params[name]["kernel"]).T
                getattr(ref, name).weight.copy_(torch.from_numpy(w.copy()))
        ref.eval()

        (sin, cos), mask = rel
        rel_torch = (
            (torch.from_numpy(np.asarray(sin)), torch.from_numpy(np.asarray(cos))),
            torch.from_numpy(np.asarray(mask)),
        )
        with torch.no_grad():
            ref_out = ref(torch.from_numpy(x_np), rel_torch).numpy()
        out = np.asarray(msr.apply({"params": params}, jnp.asarray(x_np), rel))
        np.testing.assert_allclose(ref_out, out, atol=2e-4)


class TestRetNetDecoder:
    def _cfg(self, **kw):
        base = dict(
            decoder_embed_dim=32,
            decoder_value_embed_dim=64,
            decoder_retention_heads=4,
            decoder_ffn_embed_dim=64,
            decoder_layers=2,
            vocab_size=VOCAB,
            dropout=0.0,
            drop_path_rate=0.0,
        )
        return RetNetConfig(**{**base, **kw})

    def test_forward_and_chunkwise_padding(self, rng):
        tokens = jnp.asarray(rng.integers(0, VOCAB, (2, 10)), jnp.int32)
        dec_par = RetNetDecoder(self._cfg())
        params = dec_par.init(jax.random.PRNGKey(0), tokens)["params"]
        out_par = dec_par.apply({"params": params}, tokens)["decoder_out"]
        assert out_par.shape == (2, 10, VOCAB)

        # chunk size 4 does not divide 10 -> pad + slice path
        dec_chunk = RetNetDecoder(
            self._cfg(chunkwise_recurrent=True, recurrent_chunk_size=4)
        )
        out_chunk = dec_chunk.apply({"params": params}, tokens)["decoder_out"]
        assert out_chunk.shape == (2, 10, VOCAB)

        # the SHARP contract of the pad+slice path is causality: padding
        # 10 -> 12 must be indistinguishable (for the 10 real positions)
        # from a genuine 12-token input sharing the first 10 tokens —
        # pad rows may differ, but retention is causal so they can reach
        # nothing real. This is exact, not approximate.
        tokens12 = jnp.concatenate(
            [tokens, jnp.asarray(rng.integers(0, VOCAB, (2, 2)), jnp.int32)],
            axis=1,
        )
        out_chunk12 = dec_chunk.apply(
            {"params": params}, tokens12
        )["decoder_out"]
        np.testing.assert_allclose(
            np.asarray(out_chunk12[:, :10]), np.asarray(out_chunk), atol=1e-5
        )

        # parallel vs chunkwise is the MODE gap (clamp()ed detached
        # denominators weight the inner/cross branches differently —
        # same scheme as the reference): tighter geometries pin it at
        # 2e-2 in test_parallel_matches_chunkwise; the padded partial
        # final chunk amplifies the clamp mismatch (measured max-abs
        # ~8e-2 here, concentrated from the second chunk on), so this
        # comparison only guards against gross divergence
        np.testing.assert_allclose(
            np.asarray(out_par), np.asarray(out_chunk), atol=1.5e-1
        )

    def test_recurrent_decode_matches_parallel(self, rng):
        T = 5
        tokens = jnp.asarray(rng.integers(0, VOCAB, (1, T)), jnp.int32)
        dec = RetNetDecoder(self._cfg())
        variables = dec.init(
            jax.random.PRNGKey(0), tokens[:, :1], decode=True
        )
        params, cache = variables["params"], variables["cache"]
        full = dec.apply({"params": params}, tokens)["decoder_out"]
        outs = []
        for t in range(T):
            out, mods = dec.apply(
                {"params": params, "cache": cache},
                tokens[:, t : t + 1],
                decode=True,
                decode_position=t,
                mutable=["cache"],
            )
            cache = mods["cache"]
            outs.append(out["decoder_out"][:, 0])
        stepped = jnp.stack(outs, axis=1)
        np.testing.assert_allclose(np.asarray(full), np.asarray(stepped), atol=5e-2)


class TestBertInit:
    def test_trunc_normal_redraw(self, rng):
        from gigapath_tpu.architecture.encoder import Encoder
        from gigapath_tpu.architecture.config import EncoderConfig
        from gigapath_tpu.architecture.init import init_bert_params

        cfg = EncoderConfig(
            encoder_embed_dim=64,
            encoder_attention_heads=4,
            encoder_ffn_embed_dim=128,
            encoder_layers=1,
            vocab_size=VOCAB,
        )
        enc = Encoder(cfg)
        tokens = jnp.zeros((1, 4), jnp.int32)
        params = enc.init(jax.random.PRNGKey(0), tokens)["params"]
        redrawn = init_bert_params(params, jax.random.PRNGKey(1))

        fc1 = np.asarray(redrawn["layers_0"]["ffn"]["fc1"]["kernel"])
        assert abs(fc1.std() - 0.02) < 0.005
        # truncation at +-2 of the unit draw, rescaled by 1/0.8796 so the
        # delivered std is exactly 0.02
        assert np.abs(fc1).max() <= 2 * 0.02 / 0.87962566 + 1e-6
        q = np.asarray(redrawn["layers_0"]["self_attn"]["q_proj"]["kernel"])
        assert abs(q.std() - 0.02 / np.sqrt(2)) < 0.005
        # biases untouched
        b = np.asarray(redrawn["layers_0"]["ffn"]["fc1"]["bias"])
        assert (b == 0).all()
