"""Multi-threaded serving stress under the lock-order sanitizer.

The serving stack's full concurrency surface — N submitter threads,
cache hits, in-flight joins, the background dispatch worker, and a
forced poisoned-batch bisection — driven in ONE subprocess with
``GIGAPATH_LOCKTRACE=1``, so every library lock is wrapped and every
acquisition order recorded. The run must:

1. produce EXACT metric counts (submits / cache hits / joins / slides
   served / poisoned) — concurrency may reorder work but never lose or
   double-count it;
2. record ZERO sanitizer violations (no order inversions, no
   non-reentrant re-acquires) while all of that interleaves;
3. dump a locktrace whose observed acquisition orders are fully covered
   by gigarace's static lock graph (``--validate`` exit 0) — the
   ISSUE's static-vs-runtime no-drift acceptance, under load rather
   than a smoke.

The subprocess is required because locktrace reads its env flag once at
import (the off-path must stay plain primitives; tests/test_locktrace.py
pins that side).
"""

import json
import os
import subprocess
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

# Phase plan (deterministic counts by construction):
#   A. 4 threads x 3 unique slides, worker NOT started -> 12 queued
#      requests; then 4 duplicate-content submits -> 4 in-flight joins;
#      drain() -> 12 slides served.
#   B. 4 threads resubmit all 12 contents -> 12 cache hits (resolved
#      futures, no dispatch).
#   C. chaos poison@bad: 1 poisoned + 2 good slides in one bucket ->
#      bisection isolates the bad future, 2 more slides served.
#   D. worker STARTED: 4 threads x 2 new slides race the dispatch
#      thread -> 8 more slides served through the async path.
# Totals: submits 39, cache hits 12, joins 4, served 22, poisoned 1.
_SCRIPT = r"""
import json, sys
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from gigapath_tpu.obs import locktrace
assert locktrace.enabled(), "stress run requires GIGAPATH_LOCKTRACE=1"

from gigapath_tpu.serve.service import ServeConfig, SlideService

def forward(params, embeds, coords, pad_mask):
    m = pad_mask[..., None].astype(embeds.dtype)
    return (embeds * m).sum(axis=1) / m.sum(axis=1).clip(1.0)

out_dir = sys.argv[1]
config = ServeConfig(
    max_batch=4, max_wait_s=0.01, bucket_min=16, bucket_growth=2.0,
    bucket_max=32, bucket_align=16, feature_dim=8, artifact_dir=None,
)
service = SlideService(forward, {}, config=config, out_dir=out_dir,
                       identity="stress")
rng = np.random.default_rng(0)

def mk(n):
    return (rng.normal(size=(n, 8)).astype(np.float32),
            rng.uniform(0, 1000, (n, 2)).astype(np.float32))

N_THREADS, PER = 4, 3
uniq = {f"u{t}_{i}": mk(4 + 2 * t + i)
        for t in range(N_THREADS) for i in range(PER)}

# -- phase A: concurrent unique submits + in-flight joins (no worker) --
futs = {}
def submit_batch(t):
    return [(sid, service.submit(sid, *uniq[sid]))
            for sid in (f"u{t}_{i}" for i in range(PER))]
with ThreadPoolExecutor(max_workers=N_THREADS) as pool:
    for lst in pool.map(submit_batch, range(N_THREADS)):
        futs.update(dict(lst))
for t in range(N_THREADS):
    sid = f"u{t}_0"
    jf = service.submit(f"dup_{t}", *uniq[sid])
    assert jf is futs[sid], "duplicate content must join the pending request"
service.drain()
results = {sid: f.result(timeout=60) for sid, f in futs.items()}
assert all(np.isfinite(r).all() for r in results.values())

# -- phase B: every content again -> pure cache hits ------------------
def hit(sid):
    f = service.submit("hit_" + sid, *uniq[sid])
    return np.allclose(f.result(timeout=60), results[sid])
with ThreadPoolExecutor(max_workers=N_THREADS) as pool:
    assert all(pool.map(hit, sorted(uniq)))

# -- phase C: forced poisoned-batch bisection --------------------------
fb = service.submit("bad", *mk(6))
f1 = service.submit("good1", *mk(7))
f2 = service.submit("good2", *mk(9))
service.drain()
try:
    fb.result(timeout=60)
    raise SystemExit("poisoned future must raise")
except Exception as e:
    assert "poison" in str(e), f"unexpected failure: {e!r}"
assert np.isfinite(f1.result(timeout=60)).all()
assert np.isfinite(f2.result(timeout=60)).all()

# -- phase D: the async worker races 4 submitter threads --------------
service.start()
def late(t):
    return [service.submit(f"late{t}_{i}", *mk(5 + t + i)).result(timeout=60)
            for i in range(2)]
with ThreadPoolExecutor(max_workers=N_THREADS) as pool:
    late_results = [r for lst in pool.map(late, range(N_THREADS))
                    for r in lst]
assert all(np.isfinite(r).all() for r in late_results)

stats = service.stats()
counters = {c: service.metrics.counter(c).value
            for c in ("serve.submits", "serve.cache_hits",
                      "serve.inflight_joins", "serve.slides")}
service.close()
trace = locktrace.summary()
print(json.dumps({"stats": stats, "counters": counters,
                  "violations": trace["violations"],
                  "observed_edges": trace["edges"]}))
"""


def test_serve_stress_under_locktrace(tmp_path):
    trace_path = tmp_path / "trace.jsonl"
    script = tmp_path / "stress.py"
    script.write_text(_SCRIPT)
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": REPO_ROOT,
        "GIGAPATH_LOCKTRACE": "1",
        "GIGAPATH_LOCKTRACE_OUT": str(trace_path),
        "GIGAPATH_CHAOS": "poison@bad",
    })
    proc = subprocess.run(
        [sys.executable, str(script), str(tmp_path / "obs")],
        capture_output=True, text=True, cwd=REPO_ROOT, env=env,
        timeout=540,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout.strip().splitlines()[-1])

    # exact counts: the concurrency changed the order, never the totals
    assert payload["counters"] == {
        "serve.submits": 39.0,
        "serve.cache_hits": 12.0,
        "serve.inflight_joins": 4.0,
        "serve.slides": 22.0,
    }
    stats = payload["stats"]
    assert stats["slides_served"] == 22
    assert stats["inflight_joins"] == 4
    assert stats["poisoned_requests"] == 1
    assert stats["bisections"] >= 1, "chaos poison must force a bisection"
    assert stats["cache"]["hits"] == 12
    assert stats["unexpected_retraces"] == 0

    # the sanitizer saw the whole interleaving and found nothing
    assert payload["violations"] == []
    assert payload["observed_edges"], (
        "the stress run must actually exercise nested acquisitions"
    )

    # static-vs-runtime no-drift: every observed order is a static edge
    proc = subprocess.run(
        [sys.executable, "-m", "tools.gigarace", "--validate",
         str(trace_path)],
        capture_output=True, text=True, cwd=REPO_ROOT, timeout=540,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "0 runtime violation(s), 0 problem(s)" in proc.stderr
