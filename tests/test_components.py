"""Unit tests for the small torchscale-parity components."""

import jax
import jax.numpy as jnp
import numpy as np

from gigapath_tpu.ops.droppath import DropPath
from gigapath_tpu.ops.feedforward import GLU, FeedForwardNetwork, get_activation_fn
from gigapath_tpu.ops.multiway import MultiwayNetwork
from gigapath_tpu.ops.norms import RMSNorm
from gigapath_tpu.ops.relative_position_bias import RelativePositionBias, relative_position_bucket
from gigapath_tpu.ops.xpos import apply_xpos


def test_ffn_shapes_and_subln(rng):
    ffn = FeedForwardNetwork(embed_dim=16, ffn_dim=32, subln=True)
    x = jnp.asarray(rng.normal(size=(2, 5, 16)), jnp.float32)
    params = ffn.init(jax.random.PRNGKey(0), x)
    assert "ffn_layernorm" in params["params"]
    out = ffn.apply(params, x)
    assert out.shape == x.shape


def test_glu_shapes(rng):
    glu = GLU(embed_dim=16, ffn_dim=32, activation_fn="swish")
    x = jnp.asarray(rng.normal(size=(2, 5, 16)), jnp.float32)
    params = glu.init(jax.random.PRNGKey(0), x)
    # bias-free by parity with reference gate_linear_unit.py
    assert "bias" not in params["params"]["fc1"]
    assert glu.apply(params, x).shape == x.shape


def test_activation_fns():
    for name in ["relu", "gelu", "swish"]:
        assert get_activation_fn(name) is not None
    try:
        get_activation_fn("nope")
        raise AssertionError("should have raised")
    except NotImplementedError:
        pass


def test_rmsnorm_matches_formula(rng):
    x = rng.normal(size=(2, 7, 8)).astype(np.float32)
    norm = RMSNorm(dim=8)
    params = norm.init(jax.random.PRNGKey(0), jnp.asarray(x))
    out = norm.apply(params, jnp.asarray(x))
    expected = x / np.sqrt((x**2).mean(-1, keepdims=True) + 1e-6)
    np.testing.assert_allclose(np.asarray(out), expected, atol=1e-5)


def test_droppath_eval_identity_train_scales(rng):
    dp = DropPath(drop_prob=0.5)
    x = jnp.ones((64, 3, 4))
    params = dp.init({"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)}, x, False)
    out_eval = dp.apply(params, x, True)
    np.testing.assert_array_equal(np.asarray(out_eval), np.asarray(x))
    out_train = dp.apply(params, x, False, rngs={"dropout": jax.random.PRNGKey(2)})
    vals = np.unique(np.asarray(out_train))
    assert set(np.round(vals, 4)) <= {0.0, 2.0}  # dropped or rescaled by 1/keep


def test_relative_position_bucket_properties():
    rel = jnp.arange(-50, 50)
    buckets = relative_position_bucket(rel, num_buckets=32, max_distance=128)
    b = np.asarray(buckets)
    assert b.min() >= 0 and b.max() < 32
    assert b[50] == 0  # zero offset -> bucket 0


def test_relative_position_bias_module():
    mod = RelativePositionBias(num_buckets=32, max_distance=128, n_heads=4)
    params = mod.init(jax.random.PRNGKey(0), 2, 5, 5)
    out = mod.apply(params, 2, 5, 5)
    assert out.shape == (2 * 4, 5, 5)


def test_xpos_scaling_antisymmetry(rng):
    """q-upscale and k-downscale cancel: scaled dot q·k == rotary-only dot."""
    x = rng.normal(size=(1, 9, 2, 8)).astype(np.float32)
    q = np.asarray(apply_xpos(jnp.asarray(x), downscale=False))
    k = np.asarray(apply_xpos(jnp.asarray(x), downscale=True))
    # at equal positions the xpos scales cancel exactly
    dots_qk = (q * k).sum(-1)
    base = np.asarray(apply_xpos(jnp.asarray(x), scale_base=10**9, downscale=False))
    dots_base = (base * base).sum(-1)
    np.testing.assert_allclose(dots_qk, dots_base, rtol=1e-3, atol=1e-3)


def test_multiway_split(rng):
    import flax.linen as nn
    from functools import partial

    mod = MultiwayNetwork(module_fn=partial(nn.Dense, 8), dim=1)
    x = jnp.asarray(rng.normal(size=(2, 6, 8)), jnp.float32)
    params = mod.init(jax.random.PRNGKey(0), x, split_position=3)
    full = mod.apply(params, x, split_position=3)
    a_only = mod.apply(params, x, split_position=-1)
    b_only = mod.apply(params, x, split_position=0)
    np.testing.assert_allclose(np.asarray(full[:, :3]), np.asarray(a_only[:, :3]), atol=1e-6)
    np.testing.assert_allclose(np.asarray(full[:, 3:]), np.asarray(b_only[:, 3:]), atol=1e-6)
