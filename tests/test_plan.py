"""Geometry-keyed ExecutionPlan dispatch (gigapath_tpu/plan/).

The contracts this file pins (ISSUE acceptance):

- registry round-trip + corrupt-refusal (manifest-discipline file);
- flag-vs-plan precedence: env flags win where PRESENT (including an
  explicit =0 off), the blessed plan fills the rest, defaults last;
- resolution determinism: same shapes -> same resolved plan -> ONE jit
  cache entry across a plan-routed batch loop (zero unexpected
  retraces);
- golden-ledger parity: with an empty registry and no env flags, the
  plan path traces the byte-identical program flags-only dispatch does;
- a blessed plan changes dispatch with zero env flags set (distinct
  jit key + distinct ledger fingerprint) — the in-process twin of
  ``scripts/autotune.py --selftest``, which runs end to end here too;
- the serving AOT artifact identity folds the RESOLVED plan signature,
  so a registry edit can never load a stale-plan executable;
- the tile-encoder factory's quant tier resolves through the seam.
"""

import functools
import json
import os
import subprocess
import sys
import warnings

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from gigapath_tpu.ops.dilated_attention import dilated_attention_fused
from gigapath_tpu.ops.pallas_dilated import (
    FLAG_ENV,
    PipelineFlags,
    snapshot_flags,
)
from gigapath_tpu.plan import (
    CorruptPlanRegistry,
    ExecutionPlan,
    apply_plan,
    bless_plan,
    geometry_key,
    load_registry,
    new_registry,
    plan_stats,
    registry_path,
    reset_plan_state,
    resolve_plan,
    save_registry,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SEGS, RATIOS = [16, 32], [1, 2]


@pytest.fixture
def qkv():
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=(1, 64, 4, 8)), jnp.float32)
    return q, q, q


@pytest.fixture
def clean_env(monkeypatch, tmp_path):
    """Zero kernel env flags + a private registry path, plan cache
    reset on both sides (tests must never see each other's registry)."""
    for name in list(FLAG_ENV.values()) + ["GIGAPATH_PLAN"]:
        monkeypatch.delenv(name, raising=False)
    registry = str(tmp_path / "PLAN_REGISTRY.json")
    monkeypatch.setenv("GIGAPATH_PLAN_REGISTRY", registry)
    reset_plan_state()
    yield registry
    reset_plan_state()


# ---------------------------------------------------------------------------
# registry file
# ---------------------------------------------------------------------------

class TestRegistry:
    def test_round_trip(self, clean_env):
        plan = ExecutionPlan(
            fusion="stream",
            branches=((16, 1, "", 256), (32, 2, "pipelined", 512)),
            pipe_block_k=512,
        )
        doc = new_registry()
        doc["entries"]["k|sig"] = plan.as_dict()
        save_registry(doc, clean_env)
        again = load_registry(clean_env)
        assert ExecutionPlan.from_dict(again["entries"]["k|sig"]) == plan

    def test_missing_file_is_empty(self, clean_env):
        assert load_registry(clean_env)["entries"] == {}

    def test_corrupt_refusal(self, clean_env):
        save_registry(new_registry(), clean_env)
        with open(clean_env, "a", encoding="utf-8") as fh:
            fh.write("junk")
        with pytest.raises(CorruptPlanRegistry):
            load_registry(clean_env)

    def test_digest_mismatch_refusal(self, clean_env):
        doc = new_registry()
        doc["entries"]["k"] = {"fusion": "stream"}
        save_registry(doc, clean_env)
        body = json.load(open(clean_env, encoding="utf-8"))
        body["entries"]["k"]["fusion"] = "streaming"  # edit without re-hash
        with open(clean_env, "w", encoding="utf-8") as fh:
            json.dump(body, fh)
        with pytest.raises(CorruptPlanRegistry):
            load_registry(clean_env)

    def test_corrupt_registry_resolves_to_defaults(self, clean_env, qkv):
        q, k, v = qkv
        bless_plan(geometry_key("dilated_fused", qkv),
                   ExecutionPlan(fusion="stream").as_dict(), path=clean_env)
        with open(clean_env, "a", encoding="utf-8") as fh:
            fh.write("rot")
        reset_plan_state()
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            resolved = resolve_plan("dilated_fused", qkv)
        assert resolved == PipelineFlags()

    def test_atomic_save_leaves_no_tmp(self, clean_env):
        save_registry(new_registry(), clean_env)
        parent = os.path.dirname(clean_env)
        assert not [p for p in os.listdir(parent) if p.startswith(".tmp-")]

    def test_env_registry_path_wins(self, clean_env):
        assert registry_path() == os.path.abspath(clean_env)


# ---------------------------------------------------------------------------
# precedence + resolution
# ---------------------------------------------------------------------------

class TestPrecedence:
    def test_empty_registry_resolves_to_snapshot(self, clean_env, qkv):
        assert resolve_plan("dilated_fused", qkv) == snapshot_flags()
        assert resolve_plan("dilated_fused", qkv) == PipelineFlags()

    def test_plan_fills_unset_fields(self, clean_env, qkv):
        key = geometry_key("dilated_fused", qkv)
        bless_plan(key, ExecutionPlan(
            fusion="stream", pipelined_fwd=True, pipe_block_k=256,
        ).as_dict(), path=clean_env)
        reset_plan_state()
        resolved = resolve_plan("dilated_fused", qkv)
        assert resolved.stream_fusion
        assert resolved.pipelined_fwd
        assert resolved.pipe_block_k == 256
        # fields the plan has no opinion on keep their defaults
        assert not resolved.pack_direct and resolved.quant_tile == ""

    def test_present_env_flag_beats_plan(self, clean_env, qkv, monkeypatch):
        key = geometry_key("dilated_fused", qkv)
        bless_plan(key, ExecutionPlan(
            fusion="stream", pipelined_fwd=True,
        ).as_dict(), path=clean_env)
        # an explicit =0 is PRESENT: it pins the field off over the plan
        monkeypatch.setenv("GIGAPATH_STREAM_FUSION", "0")
        monkeypatch.setenv("GIGAPATH_PIPELINED_ATTN", "1")
        reset_plan_state()
        resolved = resolve_plan("dilated_fused", qkv)
        assert not resolved.stream_fusion
        assert resolved.pipelined_fwd

    def test_env_pipelined_strips_branch_variants(self, clean_env, qkv,
                                                  monkeypatch):
        key = geometry_key("dilated_fused", qkv)
        bless_plan(key, ExecutionPlan(
            branches=((16, 1, "serial", 256),),
        ).as_dict(), path=clean_env)
        monkeypatch.setenv("GIGAPATH_PIPELINED_ATTN", "1")
        reset_plan_state()
        resolved = resolve_plan("dilated_fused", qkv)
        # env wins: variant stripped, the blessed block survives
        assert resolved.branch_plans == ((16, 1, "", 256),)

    def test_env_pipelined_bwd_survives_serial_variant(self, clean_env,
                                                       qkv, monkeypatch):
        """A per-branch "serial" variant pins the FORWARD only: an
        explicitly set GIGAPATH_PIPELINED_BWD keeps authority over the
        backward (env presence wins, the precedence contract)."""
        from gigapath_tpu.ops.pallas_dilated import _branch_pipelined

        key = geometry_key("dilated_fused", qkv)
        bless_plan(key, ExecutionPlan(
            branches=((16, 1, "serial", 0),),
        ).as_dict(), path=clean_env)
        monkeypatch.setenv("GIGAPATH_PIPELINED_BWD", "1")
        reset_plan_state()
        resolved = resolve_plan("dilated_fused", qkv)
        assert resolved.pipelined_bwd
        fwd, bwd = _branch_pipelined(resolved, 16, 1)
        assert not fwd and bwd

    def test_explicit_flags_pin_dispatch(self, clean_env, qkv):
        key = geometry_key("dilated_fused", qkv)
        bless_plan(key, ExecutionPlan(fusion="stream").as_dict(),
                   path=clean_env)
        reset_plan_state()
        pinned = PipelineFlags()
        assert resolve_plan("dilated_fused", qkv, pinned) is pinned

    def test_plan_off_disables_lookup(self, clean_env, qkv, monkeypatch):
        key = geometry_key("dilated_fused", qkv)
        bless_plan(key, ExecutionPlan(fusion="stream").as_dict(),
                   path=clean_env)
        monkeypatch.setenv("GIGAPATH_PLAN", "off")
        reset_plan_state()
        assert resolve_plan("dilated_fused", qkv) == PipelineFlags()

    def test_quant_tier_via_plan(self, clean_env, qkv):
        key = geometry_key("dilated_fused", qkv)
        bless_plan(key, ExecutionPlan(quant_tile="int8").as_dict(),
                   path=clean_env)
        reset_plan_state()
        assert resolve_plan("dilated_fused", qkv).quant_tile == "int8"

    def test_unknown_quant_tier_entry_refused_not_raised(self, clean_env,
                                                         qkv):
        """A digest-valid entry with an unknown quant_tile spelling is
        refused at lookup (warn once, default dispatch) — it must never
        raise out of resolve_plan on the hot dispatch path."""
        key = geometry_key("dilated_fused", qkv)
        bless_plan(key, {"quant_tile": "int4"}, path=clean_env)
        reset_plan_state()
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            resolved = resolve_plan("dilated_fused", qkv)
        assert resolved == PipelineFlags()
        assert any("refused" in str(w.message) for w in caught)

    def test_hit_stats(self, clean_env, qkv):
        key = geometry_key("dilated_fused", qkv)
        bless_plan(key, ExecutionPlan(fusion="stream").as_dict(),
                   path=clean_env)
        reset_plan_state()
        resolve_plan("dilated_fused", qkv)        # hit
        resolve_plan("dilated_branch", qkv)       # miss (different name)
        stats = plan_stats()
        assert stats["lookups"] == 2 and stats["hits"] == 1
        assert stats["plan_hit_rate"] == pytest.approx(0.5)


# ---------------------------------------------------------------------------
# determinism + parity
# ---------------------------------------------------------------------------

def _fused(q, k, v, flags):
    return dilated_attention_fused(
        q, k, v, SEGS, RATIOS, interpret=True, flags=flags,
    )


class TestDispatch:
    def test_resolution_determinism_zero_retraces(self, clean_env, qkv):
        """Same shapes -> same resolved plan -> one jit cache entry
        across a plan-routed batch loop."""
        q, k, v = qkv
        key = geometry_key("loop", qkv)
        bless_plan(key, ExecutionPlan(
            fusion="stream", branches=((16, 1, "", 256), (32, 2, "", 256)),
        ).as_dict(), path=clean_env)
        reset_plan_state()

        @functools.partial(jax.jit, static_argnums=(3,))
        def step(q_, k_, v_, flags):
            return _fused(q_, k_, v_, flags)

        for _ in range(4):
            flags = resolve_plan("loop", qkv)  # once per call, per contract
            step(q, k, v, flags).block_until_ready()
        assert step._cache_size() == 1

    def test_golden_parity_plan_on_vs_flags_only(self, clean_env, qkv):
        """Empty registry + no env flags: the plan path resolves to the
        very same PipelineFlags and traces a program whose ledger
        fingerprint is identical to explicit flags-only dispatch (jaxpr
        str equality is spoiled only by closure object reprs inside
        pallas_call params — the eqn histogram is the golden ledger's
        own equality notion)."""
        from gigapath_tpu.obs.ledger import jaxpr_fingerprint

        q, k, v = qkv
        assert resolve_plan("dilated_fused", qkv) == PipelineFlags()

        def plan_routed(q_, k_, v_):
            return dilated_attention_fused(
                q_, k_, v_, SEGS, RATIOS, interpret=True,  # flags=None
            )

        def flags_only(q_, k_, v_):
            return _fused(q_, k_, v_, PipelineFlags())

        assert jaxpr_fingerprint(plan_routed, q, k, v) == \
            jaxpr_fingerprint(flags_only, q, k, v)

    def test_blessed_plan_changes_dispatch_without_env(self, clean_env, qkv):
        """The acceptance demonstration, in process: distinct jit cache
        entry + distinct ledger fingerprint, zero env flags set."""
        from gigapath_tpu.obs.ledger import jaxpr_fingerprint

        q, k, v = qkv
        key = geometry_key("dilated_fused", qkv)
        bless_plan(key, ExecutionPlan(fusion="stream").as_dict(),
                   path=clean_env)
        reset_plan_state()
        resolved = resolve_plan("dilated_fused", qkv)
        assert resolved != PipelineFlags()

        @functools.partial(jax.jit, static_argnums=(3,))
        def step(q_, k_, v_, flags):
            return _fused(q_, k_, v_, flags)

        out_def = step(q, k, v, PipelineFlags())
        out_plan = step(q, k, v, resolved)
        assert step._cache_size() == 2  # the distinct jit key
        fp_def = jaxpr_fingerprint(
            lambda a, b, c: _fused(a, b, c, PipelineFlags()), q, k, v)
        fp_plan = jaxpr_fingerprint(
            lambda a, b, c: _fused(a, b, c, resolved), q, k, v)
        assert fp_def != fp_plan  # the distinct ledger fingerprint
        np.testing.assert_allclose(
            np.asarray(out_def), np.asarray(out_plan), atol=2e-5,
        )

    def test_block_override_parity_fwd_and_grad(self, clean_env, qkv):
        """A blessed per-branch block changes the kernel grid, never the
        math — forward and gradients stay parity with the default."""
        q, k, v = qkv
        flags = apply_plan(ExecutionPlan(
            branches=((16, 1, "", 256), (32, 2, "", 256)),
        ), PipelineFlags())

        def loss(flags):
            def f(a, b, c):
                return (_fused(a, b, c, flags).astype(jnp.float32) ** 2).sum()

            return f

        np.testing.assert_allclose(
            np.asarray(loss(PipelineFlags())(q, k, v)),
            np.asarray(loss(flags)(q, k, v)), rtol=1e-5,
        )
        g_def = jax.grad(loss(PipelineFlags()))(q, k, v)
        g_plan = jax.grad(loss(flags))(q, k, v)
        np.testing.assert_allclose(
            np.asarray(g_def), np.asarray(g_plan), atol=1e-4,
        )


# ---------------------------------------------------------------------------
# dispatch-site satellites: serve AOT identity, tile-encoder quant tier
# ---------------------------------------------------------------------------

class TestServeArtifactIdentity:
    def test_registry_edit_changes_bucket_fingerprints(self, clean_env,
                                                       tmp_path):
        from gigapath_tpu.serve.aot import AotExecutableCache

        def forward(p, embeds, coords, pad_mask):
            return embeds.sum(axis=(1, 2))

        cache = AotExecutableCache(
            forward, {}, feature_dim=16,
            artifact_dir=str(tmp_path / "artifacts"), name="serve.forward",
        )
        before = cache.artifact_path(2, 64)
        other_before = cache.artifact_path(2, 128)
        # bless a plan under an INNER dispatch key (what production
        # blessing actually writes: the model's own dilated_attention
        # geometry, which the compiled forward resolves during its
        # trace — not the bucket-level serve key)
        bless_plan("dilated_attention|float32[1,64,4,8]",
                   ExecutionPlan(fusion="stream").as_dict(), path=clean_env)
        reset_plan_state()
        # EVERY bucket re-fingerprints: no bucket-level check can know
        # which inner keys a trace resolved, so the whole registry
        # state participates — over-invalidation (a recompile), never
        # staleness (wrong dispatch)
        assert cache.artifact_path(2, 64) != before
        assert cache.artifact_path(2, 128) != other_before

    def test_off_missing_and_empty_registry_share_identity(self, clean_env,
                                                           tmp_path,
                                                           monkeypatch):
        """Plan off / missing / empty registry all resolve to the same
        (default) dispatch, so warm restarts across those states still
        load their artifacts."""
        from gigapath_tpu.plan import plan_registry_signature

        missing = plan_registry_signature()
        save_registry(new_registry(), clean_env)
        reset_plan_state()
        empty = plan_registry_signature()
        monkeypatch.setenv("GIGAPATH_PLAN", "off")
        reset_plan_state()
        off = plan_registry_signature()
        assert missing == empty == off == "plan-none"


class TestTileEncoderPlanRouting:
    def test_quant_tier_resolves_through_plan(self, clean_env):
        from gigapath_tpu.models.tile_encoder import create_tile_encoder

        shape = jax.ShapeDtypeStruct((1, 32, 32, 3), jnp.float32)
        key = geometry_key("tile_encoder.vit_tile_enc_test", (shape,))
        bless_plan(key, ExecutionPlan(quant_tile="int8").as_dict(),
                   path=clean_env)
        reset_plan_state()
        model, _ = create_tile_encoder("", "vit_tile_enc_test")
        assert model.quant == "int8"

    def test_explicit_kwarg_pins_tier(self, clean_env):
        from gigapath_tpu.models.tile_encoder import create_tile_encoder

        shape = jax.ShapeDtypeStruct((1, 32, 32, 3), jnp.float32)
        key = geometry_key("tile_encoder.vit_tile_enc_test", (shape,))
        bless_plan(key, ExecutionPlan(quant_tile="int8").as_dict(),
                   path=clean_env)
        reset_plan_state()
        model, _ = create_tile_encoder("", "vit_tile_enc_test", quant="")
        assert model.quant == ""

    def test_no_plan_no_env_is_f32_oracle(self, clean_env):
        from gigapath_tpu.models.tile_encoder import create_tile_encoder

        model, _ = create_tile_encoder("", "vit_tile_enc_test")
        assert model.quant == "" and not model.quant_pallas


# ---------------------------------------------------------------------------
# the autotuner, end to end
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_autotune_selftest_subprocess():
    """The seeded-sweep acceptance: ``scripts/autotune.py --selftest``
    (sweep -> bless -> zero-env dispatch change -> precedence ->
    corrupt refusal). Slow tier: it compiles several interpret-mode
    candidates; the fast siblings above cover each contract in
    process."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "scripts", "autotune.py"),
         "--selftest"],
        capture_output=True, text=True, cwd=REPO_ROOT,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "autotune selftest OK" in proc.stdout


def test_autotune_sweep_emits_decision_table(tmp_path, monkeypatch):
    """Fast sibling: one tiny CPU sweep emits the adopt_plan decision
    table with the always-on gates evaluated and walltime null (CPU
    rows never pass the walltime gate, the ab_dilated discipline)."""
    for name in list(FLAG_ENV.values()) + ["GIGAPATH_PLAN"]:
        monkeypatch.delenv(name, raising=False)
    registry = str(tmp_path / "reg.json")
    monkeypatch.setenv("GIGAPATH_PLAN_REGISTRY", registry)
    reset_plan_state()
    sys.path.insert(0, os.path.join(REPO_ROOT, "scripts"))
    import autotune

    out = str(tmp_path / "AUTOTUNE.json")
    rc = autotune.main([
        "--segments", "16,32", "--ratios", "1,2", "--n", "64",
        "--heads", "4", "--head-dim", "8", "--blocks", "",
        "--registry", registry, "--json", out, "--label", "test",
    ])
    assert rc == 0
    payload = json.load(open(out, encoding="utf-8"))
    assert payload["metric"] == "autotune"
    assert payload["backend"] == "cpu"
    assert payload["best_wall_s"] is None  # walltime gate is chip-only
    assert "default" in payload["rows"]
    assert payload["rows"]["stream"]["gates_ok"] in (True, False)
    assert payload["decision"]["adopt_plan"] in (True, False)
    # CPU + no memory win => nothing blessed without --force-bless
    assert not os.path.exists(registry) or \
        load_registry(registry)["entries"] == {} or \
        payload["decision"]["blessed"]
    reset_plan_state()


class TestFoldPlanFields:
    """The streaming-fold carriers (ISSUE 20): fold_pallas /
    fold_block_q / fold_block_k / fold_branches ride ExecutionPlan
    through the same round-trip, precedence, and bless machinery as the
    dilated-attention fields."""

    def test_fold_round_trip(self, clean_env):
        plan = ExecutionPlan(
            fold_pallas=True, fold_block_q=512, fold_block_k=256,
            fold_branches=((2048, 2, 256, 128), (16384, 1, 0, 512)),
        )
        doc = new_registry()
        doc["entries"]["stream_fold|sig"] = plan.as_dict()
        save_registry(doc, clean_env)
        again = load_registry(clean_env)
        assert ExecutionPlan.from_dict(
            again["entries"]["stream_fold|sig"]
        ) == plan

    def test_fold_plan_fills_flags(self, clean_env, qkv):
        key = geometry_key("stream_fold", qkv)
        bless_plan(key, ExecutionPlan(
            fold_pallas=True, fold_block_q=512,
            fold_branches=((16, 1, 128, 128),),
        ).as_dict(), path=clean_env)
        reset_plan_state()
        resolved = resolve_plan("stream_fold", qkv)
        assert resolved.fold_pallas
        assert resolved.fold_block_q == 512
        assert resolved.fold_branches == ((16, 1, 128, 128),)
        # fields the plan has no opinion on keep their defaults
        assert resolved.fold_block_k is None
        assert not resolved.stream_fusion

    def test_env_fold_flag_beats_plan(self, clean_env, qkv, monkeypatch):
        key = geometry_key("stream_fold", qkv)
        bless_plan(key, ExecutionPlan(
            fold_pallas=True, fold_block_q=512,
            fold_branches=((16, 1, 128, 256),),
        ).as_dict(), path=clean_env)
        # an explicit =0 is PRESENT: it pins fold_pallas off over the
        # plan; the present block-q env strips the plan's per-branch
        # bq to 0 (auto) while the bk column survives untouched
        monkeypatch.setenv(FLAG_ENV["fold_pallas"], "0")
        monkeypatch.setenv(FLAG_ENV["fold_block_q"], "64")
        reset_plan_state()
        resolved = resolve_plan("stream_fold", qkv)
        assert not resolved.fold_pallas
        assert resolved.fold_block_q == 64
        assert resolved.fold_branches == ((16, 1, 0, 256),)


def test_autotune_fold_sweep_emits_decision_table(tmp_path, monkeypatch):
    """The fold-surface sibling of the dilated sweep test: one tiny CPU
    sweep over --surface fold emits candidates ranked with mask-eqn
    A/B (jnp default > 0, Pallas fold == 0) and the adopt decision."""
    for name in list(FLAG_ENV.values()) + ["GIGAPATH_PLAN"]:
        monkeypatch.delenv(name, raising=False)
    registry = str(tmp_path / "reg.json")
    monkeypatch.setenv("GIGAPATH_PLAN_REGISTRY", registry)
    reset_plan_state()
    sys.path.insert(0, os.path.join(REPO_ROOT, "scripts"))
    import autotune

    out = str(tmp_path / "AUTOTUNE_FOLD.json")
    rc = autotune.main([
        "--surface", "fold", "--segments", "16,32", "--ratios", "1,2",
        "--chunk", "64", "--valid", "256", "--heads", "4",
        "--head-dim", "8", "--blocks", "128",
        # interpret-mode emulation buffers dominate peak bytes at this
        # toy geometry (see the autotune selftest): relax the byte gate
        # so the decision machinery, not perf, is what's under test
        "--gate-rel-tol", "10.0", "--eqn-tol", "64",
        "--registry", registry, "--json", out, "--label", "test",
    ])
    assert rc == 0
    payload = json.load(open(out, encoding="utf-8"))
    assert payload["metric"] == "fold_autotune"
    assert payload["best_wall_s"] is None  # walltime gate is chip-only
    rows = payload["rows"]
    assert {"default", "fold", "fold_b128"} <= set(rows)
    assert rows["default"]["mask_eqns"] > 0
    assert rows["fold"]["mask_eqns"] == 0
    assert payload["decision"]["adopt_plan"] in (True, False)
    reset_plan_state()
