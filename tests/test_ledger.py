"""Perf ledger subsystem: fingerprints, capture, canonical ledgers,
ledger_diff, and the golden flagship ledger gate.

The ISSUE-4 acceptance contracts pinned here:

- on CPU, the flagship golden ledger regenerates cleanly: a fresh build
  of ``tests/goldens/LEDGER_flagship.json`` diffs against the checked-in
  golden with ZERO regressions (``scripts/refresh_ledger.py`` is the
  shared generator, so the golden is never a second implementation);
- injecting a synthetic regression (doubling a branch's eqn count,
  inflating FLOPs, dropping a donation) flips the verdict JSON to
  failing;
- capture through ``CompileWatchdog`` adds no visible retraces and the
  first-signature-full / later-signatures-fingerprint policy holds.
"""

import copy
import json
import os
import sys

import jax
import jax.numpy as jnp
import pytest

from gigapath_tpu.obs import (
    CompileWatchdog,
    NullLedger,
    PerfLedger,
    RunLog,
    capture_profile,
    get_ledger,
    jaxpr_fingerprint,
)
from gigapath_tpu.obs.ledger import shape_signature, write_ledger

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO_ROOT, "scripts"))

import ledger_diff  # noqa: E402
import refresh_ledger  # noqa: E402

GOLDEN = os.path.join(REPO_ROOT, "tests", "goldens", "LEDGER_flagship.json")


def read_events(path):
    with open(path) as fh:
        return [json.loads(line) for line in fh if line.strip()]


# ---------------------------------------------------------------------------
# fingerprints & profiles
# ---------------------------------------------------------------------------

class TestFingerprint:
    def test_counts_primitives_with_fixed_columns(self):
        fp = jaxpr_fingerprint(lambda x: (x @ x.T).reshape(-1), jnp.ones((4, 4)))
        assert fp["eqns_total"] >= 2
        assert fp["primitives"]["reshape"] >= 1
        # the PERFORMANCE.md columns are always present, even at zero
        for col in ("transpose", "slice", "broadcast_in_dim", "pallas_call"):
            assert col in fp["primitives"]

    def test_recurses_into_sub_jaxprs(self):
        inner = jax.jit(lambda x: x.reshape(2, 2).T)
        fp = jaxpr_fingerprint(lambda x: inner(x) + 1, jnp.ones((4,)))
        # the reshape/transpose live inside the pjit sub-jaxpr
        assert fp["primitives"]["reshape"] >= 1
        assert fp["primitives"]["transpose"] >= 1

    def test_shape_signature(self):
        sig = shape_signature(
            (jnp.ones((2, 3)), {"w": 1, "b": 2}), {"y": jnp.ones(4)}
        )
        assert sig == "float32[2,3];tree{2};y=float32[4]"


class TestCaptureProfile:
    def test_full_profile_has_cost_memory_jaxpr(self):
        p = capture_profile(lambda x: (x @ x).sum(), jnp.ones((8, 8)))
        assert p["cost"]["flops"] > 0
        assert p["memory"]["argument_bytes"] > 0
        assert p["memory"]["peak_bytes"] >= p["memory"]["argument_bytes"]
        assert p["jaxpr"]["eqns_total"] > 0

    def test_trace_only_skips_compile(self):
        p = capture_profile(lambda x: x + 1, jnp.ones(4), full=False)
        assert "cost" not in p and "memory" not in p
        assert p["jaxpr"]["eqns_total"] >= 1

    def test_donated_buffer_accounting(self):
        fn = jax.jit(lambda x: x + 1, donate_argnums=0)
        p = capture_profile(fn, jnp.ones((128,)))
        assert p["memory"]["donated_bytes"] == 512.0
        # the donated input aliases the output: peak excludes it once
        assert p["memory"]["peak_bytes"] == pytest.approx(
            p["memory"]["argument_bytes"] + p["memory"]["temp_bytes"]
        )


# ---------------------------------------------------------------------------
# PerfLedger
# ---------------------------------------------------------------------------

class TestPerfLedger:
    def test_dedup_and_canonical_rewrite(self, tmp_path):
        path = str(tmp_path / "run.ledger.json")
        led = PerfLedger(path=path)
        fn = lambda x: (x * 2).sum()  # noqa: E731
        led.capture("step", fn, jnp.ones((2, 8)))
        led.capture("step", fn, jnp.ones((2, 8)))  # same signature: dedup
        led.capture("step", fn, jnp.ones((2, 16)))
        assert len(led.entries) == 2
        first = open(path, "rb").read()
        led.write()
        assert open(path, "rb").read() == first  # canonical: stable bytes
        doc = json.loads(first)
        assert doc["v"] == 1
        assert list(doc["entries"]) == sorted(doc["entries"])

    def test_full_then_fingerprint_policy(self, tmp_path):
        led = PerfLedger(path=str(tmp_path / "l.json"))
        fn = lambda x: x.sum()  # noqa: E731
        e1 = led.capture("step", fn, jnp.ones((4,)))
        e2 = led.capture("step", fn, jnp.ones((8,)))
        e3 = led.capture_full("step", fn, jnp.ones((16,)))
        assert e1["cost"] is not None and "memory" in e1
        assert "cost" not in e2  # later signature: fingerprint-only
        assert e3["cost"] is not None  # explicit full override
        # capture_full UPGRADES an existing fingerprint-only entry
        e2b = led.capture_full("step", fn, jnp.ones((8,)))
        assert e2b["cost"] is not None

    def test_deferred_autowrite(self, tmp_path):
        """bench's mode: captures buffer in memory, the file lands only
        on the explicit success-path write()."""
        path = str(tmp_path / "l.json")
        led = PerfLedger(path=path, autowrite=False)
        led.capture_full("f", lambda x: x.sum(), jnp.ones((4,)))
        assert not os.path.exists(path)
        led.write()
        assert os.path.exists(path)

    def test_ledger_path_derives_from_runlog(self, tmp_path):
        log = RunLog(str(tmp_path / "obs" / "run.jsonl"), driver="t",
                     run_id="r-1", echo=False)
        led = get_ledger(log)
        assert led.path == str(tmp_path / "obs" / "r-1.ledger.json")
        led.capture("f", lambda x: x, jnp.ones(2))
        assert os.path.exists(led.path)
        events = read_events(log.path)
        assert [ev["kind"] for ev in events] == ["compile_profile"]
        assert events[0]["name"] == "f"
        assert events[0]["jaxpr"]["eqns_total"] >= 0
        log.close()

    def test_null_ledger_under_obs_off(self, tmp_path, monkeypatch):
        from gigapath_tpu.obs import get_run_log

        monkeypatch.setenv("GIGAPATH_OBS", "0")
        log = get_run_log("t", out_dir=str(tmp_path))
        led = get_ledger(log)
        assert isinstance(led, NullLedger) and not isinstance(led, PerfLedger)
        assert led.capture("f", lambda x: x, jnp.ones(2)) is None
        assert led.write() is None
        assert list(tmp_path.iterdir()) == []  # no files, no obs dir

    def test_capture_failure_is_contained(self, tmp_path):
        path = str(tmp_path / "run.jsonl")
        log = RunLog(path, driver="t", echo=False)
        led = get_ledger(log)
        assert led.capture("bad", lambda x: x.no_such_attr, jnp.ones(2)) is None
        (ev,) = read_events(path)
        assert ev["kind"] == "compile_profile" and "error" in ev
        log.close()


class TestWatchdogLedgerHook:
    def test_wrap_ledgers_each_new_key(self, tmp_path):
        log = RunLog(str(tmp_path / "run.jsonl"), driver="t", echo=False)
        led = get_ledger(log)
        fn = jax.jit(lambda x: x * 2)
        wd = CompileWatchdog("step", log, ledger=led)
        wrapped = wd.wrap(fn)
        for _ in range(3):
            wrapped(jnp.ones((2, 8)))
        wrapped(jnp.ones((2, 16)))
        assert len(led.entries) == 2
        # first key full, second fingerprint-only
        entries = [led.entries[k] for k in sorted(led.entries)]
        assert sum("cost" in e for e in entries) == 1
        log.close()

    def test_profile_method_for_record_surface_loops(self, tmp_path):
        led = PerfLedger(path=str(tmp_path / "l.json"))
        wd = CompileWatchdog("train_step", ledger=led)
        wd.record((1, 128), 0.5)
        wd.profile((1, 128), lambda x: x.sum(), jnp.ones((1, 128)))
        assert len(led.entries) == 1
        wd2 = CompileWatchdog("train_step")  # no ledger: a no-op
        wd2.profile((1, 128), lambda x: x.sum(), jnp.ones((1, 128)))


# ---------------------------------------------------------------------------
# ledger_diff
# ---------------------------------------------------------------------------

class TestLedgerDiff:
    def test_selftest_passes(self):
        assert ledger_diff.selftest() == 0

    def test_cli_missing_file_exits_2(self, tmp_path):
        missing = str(tmp_path / "nope.json")
        assert ledger_diff.main([missing, missing]) == 2

    def test_cli_roundtrip_and_verdict_json(self, tmp_path):
        led = PerfLedger(path=str(tmp_path / "a.json"))
        led.capture("f", lambda x: (x @ x).sum(), jnp.ones((8, 8)))
        base, cand = str(tmp_path / "a.json"), str(tmp_path / "b.json")
        doc = json.loads(open(base).read())
        write_ledger(doc, cand)
        out = str(tmp_path / "verdict.json")
        assert ledger_diff.main([base, cand, "--json", out]) == 0
        verdict = json.load(open(out))
        assert verdict["decision"]["ok"] is True

        # synthetic regression: eqn growth must flip the CLI to rc=1
        doc2 = copy.deepcopy(doc)
        entry = next(iter(doc2["entries"].values()))
        entry["jaxpr"]["eqns_total"] += 5
        write_ledger(doc2, cand)
        assert ledger_diff.main([base, cand, "--json", out]) == 1
        verdict = json.load(open(out))
        assert verdict["decision"]["ok"] is False


# ---------------------------------------------------------------------------
# the golden flagship ledger (ISSUE acceptance)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def fresh_flagship():
    """Build the flagship ledger ONCE per test module (the expensive
    part: ~15 s of tracing + one tiny-slide-encoder compile on CPU)."""
    ledger, meta = refresh_ledger.build_golden_ledger()
    return {
        "v": 1, **meta,
        "entries": {k: ledger.entries[k] for k in sorted(ledger.entries)},
    }


def test_golden_ledger_regenerates_clean(fresh_flagship):
    """Acceptance: on CPU the regenerated flagship ledger diffs against
    the checked-in golden with zero regressions."""
    golden = ledger_diff.load_ledger(GOLDEN)
    verdict = ledger_diff.compare(golden, fresh_flagship)
    assert verdict["decision"]["regressions"] == 0, verdict["decision"]["regressed"]
    assert verdict["decision"]["ok"] is True
    # and the diff is exact, not merely within tolerance: goldens are
    # regenerated in this very environment
    assert verdict["decision"]["improvements"] == 0
    assert verdict["notes"] == []


def test_golden_covers_the_round6_signal(fresh_flagship):
    """The golden pins the round-6 PERFORMANCE.md table's machine form:
    the stream epilogue admits ZERO dense-glue transpose/slice/broadcast
    eqns while the dense fused path still materializes them."""
    entries = fresh_flagship["entries"]
    stream = next(v for k, v in entries.items()
                  if k.startswith("dilated_stream_fwd"))
    fused = next(v for k, v in entries.items()
                 if k.startswith("dilated_fused_fwd"))
    for prim in ("transpose", "slice", "broadcast_in_dim"):
        assert stream["jaxpr"]["primitives"][prim] == 0, prim
        assert fused["jaxpr"]["primitives"][prim] > 0, prim
    assert stream["jaxpr"]["eqns_total"] < fused["jaxpr"]["eqns_total"]
    slide = next(v for k, v in entries.items()
                 if k.startswith("slide_enc_tiny_fwd"))
    assert slide["cost"]["flops"] > 0
    assert slide["memory"]["peak_bytes"] > 0


def test_golden_covers_the_ring_signal(fresh_flagship):
    """The golden pins the ring acceptance: the ring path's traced
    program moves K/V exclusively by ppermute (ZERO all_gather — the
    hoisted counts gather does not exist on the unmasked golden shape),
    the reverse ring of the custom VJP adds its own permutes, and the
    gather baseline still materializes one all_gather per K/V tensor."""
    entries = fresh_flagship["entries"]

    def entry(prefix):
        return next(v for k, v in entries.items() if k.startswith(prefix))

    ring_fwd = entry("dilated_ring_fwd")["jaxpr"]["primitives"]
    ring_grad = entry("dilated_ring_grad")["jaxpr"]["primitives"]
    gather_fwd = entry("dilated_ring_gather_fwd")["jaxpr"]["primitives"]
    assert ring_fwd["all_gather"] == 0
    assert ring_fwd["ppermute"] > 0
    assert ring_grad["all_gather"] == 0
    assert ring_grad["ppermute"] > ring_fwd["ppermute"]  # reverse ring
    assert gather_fwd["all_gather"] == 2  # K and V, full segment
    assert gather_fwd["ppermute"] == 0


def test_golden_covers_the_fold_signal(fresh_flagship):
    """The golden pins the Pallas streaming-fold acceptance (ISSUE 20):
    the Pallas fold traces ZERO dense mask equations (masks become
    in-kernel iota comparisons inside the opaque pallas_call) while the
    jnp control still materializes square bool masks — and the compiled
    Pallas fold lowers with strictly fewer temp bytes than the jnp fold
    at the 16k smoke geometry."""
    entries = fresh_flagship["entries"]

    def entry(prefix):
        return next(v for k, v in entries.items() if k.startswith(prefix))

    jnp_e = entry("stream_fold_jnp|")
    pallas_e = entry("stream_fold_pallas|")
    assert jnp_e["jaxpr"]["mask"] > 0
    assert pallas_e["jaxpr"]["mask"] == 0
    assert pallas_e["jaxpr"]["primitives"]["pallas_call"] >= 1
    assert jnp_e["jaxpr"]["primitives"].get("pallas_call", 0) == 0
    # grads keep the discipline: stored-lse bwd, still zero dense masks
    assert entry("stream_fold_jnp_grad")["jaxpr"]["mask"] > 0
    assert entry("stream_fold_pallas_grad")["jaxpr"]["mask"] == 0
    # the compiled-memory half of the acceptance pin
    assert pallas_e["memory"]["temp_bytes"] < jnp_e["memory"]["temp_bytes"]
    assert pallas_e["memory"]["peak_bytes"] < jnp_e["memory"]["peak_bytes"]


def test_ring_per_shard_bytes_scale_with_chunk_not_segment(tmp_path):
    """Acceptance: ledger_diff over gather->ring compiled profiles shows
    the oversized branch's temp bytes scaling with the LOCAL CHUNK, not
    the segment — the gather path materializes the full-segment K/V on
    every shard (plus full-width logits), the ring only chunk-sized
    buffers. Captured through the perf ledger on an 8-way CPU mesh."""
    from jax.sharding import Mesh, PartitionSpec as P

    import numpy as np

    from gigapath_tpu.ops.dilated_attention import dilated_attention
    from gigapath_tpu.ops.pallas_dilated import PipelineFlags
    from gigapath_tpu.parallel.sharding import shard_map_compat

    shard_map, check_kw = shard_map_compat()
    L, H, Dh, ndev = 512, 4, 8, 8  # one oversized branch: sl == L, 8 ranks
    mesh = Mesh(np.array(jax.devices()[:ndev]), ("seq",))
    q = jnp.ones((1, L, H, Dh), jnp.float32)

    def sp_fn(ring):
        return jax.jit(shard_map(
            lambda q, k, v: dilated_attention(
                q, k, v, [L], [1], seq_axis_name="seq", seq_axis_size=ndev,
                flags=PipelineFlags(ring_attn=ring),
            ),
            mesh=mesh, in_specs=(P(None, "seq"),) * 3,
            out_specs=P(None, "seq"), **check_kw,
        ))

    docs = {}
    for name, ring in (("gather", False), ("ring", True)):
        led = PerfLedger(path=str(tmp_path / f"{name}.json"))
        entry = led.capture_full("dilated_oversized_branch", sp_fn(ring),
                                 q, q, q)
        assert entry["memory"]["temp_bytes"] is not None
        docs[name] = json.loads(open(led.path).read())

    verdict = ledger_diff.compare(docs["gather"], docs["ring"])
    rows = next(iter(verdict["entries"].values()))
    temp_row = next(r for r in rows if r["metric"] == "memory.temp_bytes")
    # the ring variant must be a reported IMPROVEMENT, and by more than
    # threshold noise: the gather path's per-shard temps carry the full
    # 8x-local-length K/V copies that the ring never materializes.
    # (decision.ok is NOT asserted: ring-vs-gather are different traced
    # programs, so the jaxpr eqn columns legitimately differ both ways.)
    assert temp_row["verdict"] == "improvement", temp_row
    assert temp_row["candidate"] < 0.6 * temp_row["baseline"], temp_row


def test_synthetic_regression_flips_verdict(tmp_path):
    """Acceptance: doubling a branch's eqn count in a copy of the golden
    flips the ledger_diff verdict JSON to failing."""
    golden = ledger_diff.load_ledger(GOLDEN)
    regressed = copy.deepcopy(golden)
    key = next(k for k in regressed["entries"]
               if k.startswith("dilated_stream_fwd"))
    entry = regressed["entries"][key]
    entry["jaxpr"]["eqns_total"] *= 2
    entry["jaxpr"]["primitives"]["pallas_call"] *= 2
    cand = str(tmp_path / "regressed.json")
    write_ledger(regressed, cand)
    out = str(tmp_path / "verdict.json")
    rc = ledger_diff.main([GOLDEN, cand, "--json", out])
    assert rc == 1
    verdict = json.load(open(out))
    assert verdict["decision"]["ok"] is False
    assert any("pallas_call" in line for line in verdict["decision"]["regressed"])


def test_refresh_refuses_to_overwrite_on_regression(tmp_path, monkeypatch):
    """scripts/refresh_ledger.sh contract: regeneration that would regress
    the golden exits 1 and leaves the file untouched unless --force."""
    golden_doc = ledger_diff.load_ledger(GOLDEN)
    fresh = copy.deepcopy(golden_doc)
    key = next(iter(fresh["entries"]))
    fresh["entries"][key]["jaxpr"]["eqns_total"] += 100  # a would-be regression

    class FakeLedger:
        entries = fresh["entries"]

    meta = {k: v for k, v in fresh.items() if k not in ("v", "entries")}
    monkeypatch.setattr(refresh_ledger, "build_golden_ledger",
                        lambda: (FakeLedger(), meta))
    target = str(tmp_path / "golden.json")
    write_ledger(golden_doc, target)
    before = open(target, "rb").read()
    assert refresh_ledger.regenerate(target, force=False) == 1
    assert open(target, "rb").read() == before  # untouched
    assert refresh_ledger.regenerate(target, check=True) == 1  # --check agrees
    assert refresh_ledger.regenerate(target, force=True) == 0
    assert json.load(open(target))["entries"][key]["jaxpr"]["eqns_total"] == \
        fresh["entries"][key]["jaxpr"]["eqns_total"]
