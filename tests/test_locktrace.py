"""obs/locktrace.py: the lock-order sanitizer's two contracts.

OFF (the default): the factories return PLAIN threading primitives —
no wrapper objects, no recording state, no files. This is the
zero-overhead pin: with ``GIGAPATH_LOCKTRACE`` unset the library's
locking is byte-identical to pre-sanitizer behavior and a run leaves
no extra artifacts behind.

ON (``GIGAPATH_LOCKTRACE=1``): wrappers record acquisition-order
edges, order inversions (the 2-cycle a->b / b->a), non-reentrant
same-instance re-acquires, contention, and per-lock hold times, and
dump one JSONL payload at exit. ``python -m tools.gigarace
--validate`` consumes that payload; its record-shape expectations are
pinned here too.

Both contracts run in subprocesses with the flag pinned explicitly
(removed / set), because locktrace reads the env ONCE at import — and
so the whole suite can itself be executed under GIGAPATH_LOCKTRACE=1
(the tier-1-under-sanitizer acceptance) without perturbing either
side.
"""

import json
import os
import subprocess
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# off-path: plain primitives, zero footprint. Exercised in a subprocess
# with the flag explicitly REMOVED (symmetric to the on-path below) so
# the pin holds even when the enclosing pytest run is itself executed
# under GIGAPATH_LOCKTRACE=1 — the ISSUE's tier-1-under-sanitizer mode.
# ---------------------------------------------------------------------------

_OFF_SCRIPT = r"""
import os, sys, threading
assert os.environ.get("GIGAPATH_LOCKTRACE", "") != "1"
from gigapath_tpu.obs import locktrace

assert not locktrace.enabled()
assert locktrace.summary() is None

lk = locktrace.make_lock("test.off.lock")
rlk = locktrace.make_rlock("test.off.rlock")
cond = locktrace.make_condition("test.off.cond")
# exact stdlib factory types — not subclasses, not wrappers
assert type(lk) is type(threading.Lock())
assert type(rlk) is type(threading.RLock())
assert type(cond) is threading.Condition
# a condition built over an existing (plain) lock shares it
cond2 = locktrace.make_condition("test.off.cond2", lock=lk)
assert cond2._lock is lk

# dump() is a no-op: no file appears
out_dir = sys.argv[1]
out = os.path.join(out_dir, "trace.jsonl")
locktrace.dump(out)
assert not os.path.exists(out), "dump() must be a no-op with the flag off"
assert os.listdir(out_dir) == []

# attach_locktrace registers nothing
class FakeRunLog:
    def __init__(self):
        self.closers = []
        self.events = []
    def add_closer(self, fn):
        self.closers.append(fn)
    def event(self, kind, **payload):
        self.events.append((kind, payload))

log = FakeRunLog()
locktrace.attach_locktrace(log)
assert log.closers == [] and log.events == []
print("off-contract-ok")
"""


def test_off_contract_plain_primitives_zero_footprint(tmp_path):
    env = dict(os.environ)
    env.pop("GIGAPATH_LOCKTRACE", None)
    env.pop("GIGAPATH_LOCKTRACE_OUT", None)
    proc = subprocess.run(
        [sys.executable, "-c", _OFF_SCRIPT, str(tmp_path)],
        capture_output=True, text=True, cwd=REPO_ROOT, env=env,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    assert "off-contract-ok" in proc.stdout
    assert list(tmp_path.iterdir()) == [], (
        "the off-path run must leave no artifacts behind"
    )


# ---------------------------------------------------------------------------
# on-path: semantics, exercised in a subprocess so the import-time flag
# read sees GIGAPATH_LOCKTRACE=1
# ---------------------------------------------------------------------------

_ON_SCRIPT = r"""
import json, sys, threading
from gigapath_tpu.obs import locktrace

assert locktrace.enabled()

a = locktrace.make_lock("t.A")
b = locktrace.make_lock("t.B")
r = locktrace.make_rlock("t.R")
cond = locktrace.make_condition("t.C")

# order edge A -> B, twice
for _ in range(2):
    with a:
        with b:
            pass

# the inversion B -> A: exactly one order violation
with b:
    with a:
        pass

# RLock reentrancy is legal — no violation
with r:
    with r:
        pass

# BOUNDED same-thread probes on a held non-reentrant lock are NOT
# violations: failing fast is the sanctioned *_from_signal degradation
# (RunLog.event_from_signal's timeout=1.0 acquire on the thread it may
# have interrupted inside event())
a.acquire()
assert a.acquire(blocking=False) is False
assert a.acquire(timeout=0.01) is False
a.release()

# an INDEFINITE same-thread re-acquire IS a self-deadlock: the wrapper
# records the violation BEFORE the hanging attempt, so run it on a
# daemon thread and poll for the record (the thread stays parked; the
# process exits fine over it)
d = locktrace.make_lock("t.D")
def deadlocker():
    d.acquire()
    d.acquire()   # hangs forever; violation recorded first
t3 = threading.Thread(target=deadlocker, daemon=True)
t3.start()
import time as _time
deadline = _time.monotonic() + 10
while _time.monotonic() < deadline:
    snap = locktrace.summary()
    if any("t.D" in v for v in snap["violations"]):
        break
    _time.sleep(0.02)
else:
    raise SystemExit("self-deadlock violation never recorded")

# contention: a holder forces the non-blocking first try to fail
hold = threading.Event()
go = threading.Event()
def holder():
    with b:
        go.set()
        hold.wait(timeout=5)
t = threading.Thread(target=holder)
t.start()
go.wait(timeout=5)
acquired = threading.Event()
def contender():
    with b:
        acquired.set()
t2 = threading.Thread(target=contender)
t2.start()
import time
time.sleep(0.05)
hold.set()
t.join(timeout=5); t2.join(timeout=5)
assert acquired.is_set()

# condition wait releases and re-acquires the underlying lock
with cond:
    cond.wait(timeout=0.01)

s = locktrace.summary()
print(json.dumps(s))
"""


def _run_on_subprocess(extra_env=None, script=_ON_SCRIPT, out_path=None):
    env = dict(os.environ)
    env["GIGAPATH_LOCKTRACE"] = "1"
    if out_path is not None:
        env["GIGAPATH_LOCKTRACE_OUT"] = out_path
    else:
        env.pop("GIGAPATH_LOCKTRACE_OUT", None)
    env.update(extra_env or {})
    return subprocess.run(
        [sys.executable, "-c", script],
        capture_output=True, text=True, cwd=REPO_ROOT, env=env,
        timeout=120,
    )


def test_on_semantics_edges_violations_contention_holds():
    proc = _run_on_subprocess()
    assert proc.returncode == 0, proc.stderr
    s = json.loads(proc.stdout.strip().splitlines()[-1])
    assert s["kind"] == "locktrace"
    assert {"t.A", "t.B", "t.R", "t.C"} <= set(s["locks"])
    edges = {tuple(e) for e in s["edges"]}
    assert ("t.A", "t.B") in edges and ("t.B", "t.A") in edges
    assert s["edge_counts"]["t.A -> t.B"] == 2
    # exactly one order inversion + one INDEFINITE same-instance
    # re-acquire (the daemon-thread deadlocker on t.D)
    inversions = [v for v in s["violations"] if "order" in v]
    reacquires = [v for v in s["violations"] if "re-acquir" in v]
    assert len(inversions) == 1, s["violations"]
    assert len(reacquires) == 1 and "t.D" in reacquires[0], s["violations"]
    assert len(s["violations"]) == 2, s["violations"]
    # the rlock reentry produced NO violation mentioning t.R, and the
    # BOUNDED probes on held t.A no re-acquire violation — failing fast
    # is the sanctioned signal-path degradation, not a self-deadlock
    assert not any("t.R" in v for v in s["violations"])
    assert not any("t.A" in v for v in reacquires)
    # the blocked contender registered contention on t.B
    assert s["contention"].get("t.B", 0) >= 1
    # every primitive that was held carries hold samples
    for name in ("t.A", "t.B", "t.R", "t.C"):
        h = s["holds"][name]
        assert h["count"] >= 1
        assert h["total_ms"] >= 0.0
        assert h["p99_ms"] >= h["p50_ms"] >= 0.0


def test_on_atexit_dump_lands_at_out_path(tmp_path):
    out = tmp_path / "trace.jsonl"
    script = (
        "from gigapath_tpu.obs import locktrace\n"
        "lk = locktrace.make_lock('t.X')\n"
        "with lk:\n"
        "    pass\n"
    )
    proc = _run_on_subprocess(script=script, out_path=str(out))
    assert proc.returncode == 0, proc.stderr
    lines = [json.loads(x) for x in out.read_text().splitlines() if x.strip()]
    assert len(lines) == 1
    payload = lines[0]
    assert payload["kind"] == "locktrace"
    assert "t.X" in payload["locks"]
    assert payload["violations"] == []


def test_on_dump_appends_across_processes(tmp_path):
    """Multi-process runs (the dist smoke) share one OUT file: every
    process appends its own payload line instead of truncating."""
    out = tmp_path / "trace.jsonl"
    script = (
        "from gigapath_tpu.obs import locktrace\n"
        "lk = locktrace.make_lock('t.P')\n"
        "with lk:\n"
        "    pass\n"
    )
    for _ in range(2):
        proc = _run_on_subprocess(script=script, out_path=str(out))
        assert proc.returncode == 0, proc.stderr
    lines = [json.loads(x) for x in out.read_text().splitlines() if x.strip()]
    assert len(lines) == 2
    assert all(p["kind"] == "locktrace" for p in lines)


def test_on_payload_validates_against_its_own_locks(tmp_path):
    """The --validate consumer accepts a raw dump whose locks/edges are
    in the static model; synthetic 't.*' locks are NOT, so the
    validator must flag them — proving it actually reads the payload."""
    out = tmp_path / "trace.jsonl"
    script = (
        "from gigapath_tpu.obs import locktrace\n"
        "lk = locktrace.make_lock('t.unknown')\n"
        "with lk:\n"
        "    pass\n"
    )
    proc = _run_on_subprocess(script=script, out_path=str(out))
    assert proc.returncode == 0, proc.stderr
    proc = subprocess.run(
        [sys.executable, "-m", "tools.gigarace", "--validate", str(out)],
        capture_output=True, text=True, cwd=REPO_ROOT, timeout=300,
    )
    assert proc.returncode == 1
    assert "t.unknown" in proc.stdout
