"""On-the-fly sincos embedding must match the reference's full-table gather."""

import numpy as np
import jax.numpy as jnp

from gigapath_tpu.ops import pos_embed as pe


def test_table_matches_known_structure():
    table = pe.get_2d_sincos_pos_embed(8, 4, cls_token=True)
    assert table.shape == (17, 8)
    # cls row is zeros
    np.testing.assert_array_equal(table[0], np.zeros(8))
    # position (0,0) -> sin(0)=0, cos(0)=1 pattern
    np.testing.assert_allclose(table[1], [0, 0, 1, 1, 0, 0, 1, 1], atol=1e-7)


def test_on_the_fly_matches_table():
    ngrids, dim, tile = 16, 24, 256
    table = pe.get_2d_sincos_pos_embed(dim, ngrids, cls_token=True)
    rng = np.random.default_rng(0)
    coords = rng.integers(0, ngrids * tile, size=(2, 37, 2)).astype(np.float32)
    pos = pe.coords_to_pos(jnp.asarray(coords), tile, ngrids)
    gathered = table[np.asarray(pos)]
    on_the_fly = pe.pos_embed_for_coords(dim, jnp.asarray(coords), tile, ngrids)
    np.testing.assert_allclose(np.asarray(on_the_fly), gathered, atol=1e-5)


def test_coords_to_pos_values():
    coords = jnp.array([[[0.0, 0.0], [256.0, 512.0], [300.0, 100.0]]])
    pos = pe.coords_to_pos(coords, 256, 1000)
    np.testing.assert_array_equal(np.asarray(pos), [[1, 1 * 1000 + 2 + 1, 1 * 1000 + 0 + 1]])


def test_negative_coords_match_torch_wraparound():
    """Padded edge tiles have negative coords; reference table gather wraps
    like torch negative indexing. Verify exact emulation."""
    import torch

    ngrids, dim, tile = 8, 16, 256
    table = torch.from_numpy(pe.get_2d_sincos_pos_embed(dim, ngrids, cls_token=True))
    coords = np.array([[[-128.0, 64.0], [-256.0, -256.0], [0.0, -512.0]]], np.float32)
    pos = np.asarray(pe.coords_to_pos(jnp.asarray(coords), tile, ngrids))
    ref = table[torch.from_numpy(pos).long()].numpy()
    ours = np.asarray(pe.pos_embed_for_coords(dim, jnp.asarray(coords), tile, ngrids))
    np.testing.assert_allclose(ours, ref, atol=1e-5)


def test_interpolate_identity():
    table = pe.get_2d_sincos_pos_embed(8, 4, cls_token=True)
    out = pe.interpolate_pos_embed_table(table, 4)
    np.testing.assert_array_equal(out, table)


def test_interpolate_resize():
    table = pe.get_2d_sincos_pos_embed(8, 4, cls_token=True)
    out = pe.interpolate_pos_embed_table(table, 8)
    assert out.shape == (65, 8)
    np.testing.assert_array_equal(out[0], table[0])
