"""Mesh construction, sharding rules, SPMD train step on the 8-dev CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from gigapath_tpu.parallel.mesh import factorize, make_mesh, shard_batch_seq
from gigapath_tpu.parallel.sharding import apply_shardings, param_spec, param_shardings
from gigapath_tpu.parallel.spmd import cross_entropy_loss, make_train_step


def test_factorize():
    sizes = factorize(8, ("data", "seq", "model"))
    assert np.prod(list(sizes.values())) == 8
    assert sizes["seq"] >= sizes["model"]  # seq gets devices first


def test_make_mesh_axis_sizes():
    mesh = make_mesh(8, axis_sizes={"data": 2, "seq": 4})
    assert mesh.shape == {"data": 2, "seq": 4}
    mesh1 = make_mesh(1, axis_sizes={"data": 1})
    assert mesh1.shape == {"data": 1}


def test_param_spec_rules():
    k = jnp.zeros((4, 8))
    assert param_spec(["layers_0", "self_attn", "q_proj", "kernel"], k) == P(None, "model")
    assert param_spec(["layers_0", "self_attn", "out_proj", "kernel"], k) == P("model", None)
    assert param_spec(["ffn", "fc1", "kernel"], k) == P(None, "model")
    assert param_spec(["ffn", "fc2", "kernel"], k) == P("model", None)
    assert param_spec(["ffn", "fc1", "bias"], jnp.zeros(8)) == P()
    assert param_spec(["norm", "scale"], jnp.zeros(8)) == P()


def test_sharded_train_step_matches_single_device(rng):
    """Same batch, same init: sharded step loss == single-device step loss."""
    from gigapath_tpu.models.classification_head import ClassificationHead

    model = ClassificationHead(
        input_dim=32,
        latent_dim=64,
        feat_layer="1",
        n_classes=3,
        slide_kwargs=dict(
            embed_dim=64, depth=1, segment_length=[8, 16], dilated_ratio="[1, 2]",
            dropout=0.0, drop_path_rate=0.0,
        ),
    )
    B, N = 2, 16
    x = jnp.asarray(rng.normal(size=(B, N, 32)), jnp.float32)
    coords = jnp.asarray(rng.uniform(0, 25000, (B, N, 2)), jnp.float32)
    labels = jnp.asarray([0, 2], jnp.int32)
    params = model.init(jax.random.PRNGKey(0), x, coords)["params"]
    opt = optax.adamw(1e-3)
    step = make_train_step(model, opt)
    batch = {"images": x, "coords": coords, "labels": labels}

    def loss_and_grads(params, batch):
        def loss_fn(p):
            logits = model.apply({"params": p}, batch["images"], batch["coords"])
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, batch["labels"]
            ).mean()

        return jax.value_and_grad(loss_fn)(params)

    _, _, loss_single = jax.jit(step)(params, opt.init(params), batch, jax.random.PRNGKey(1))
    l1, g1 = jax.jit(loss_and_grads)(params, batch)

    mesh = make_mesh(8, axis_sizes={"data": 2, "seq": 2, "model": 2})
    with mesh:
        params_s = apply_shardings(params, mesh)
        opt_state_s = opt.init(params_s)
        batch_s = {
            "images": jax.device_put(x, shard_batch_seq(mesh)),
            "coords": jax.device_put(coords, shard_batch_seq(mesh)),
            "labels": jax.device_put(labels, NamedSharding(mesh, P("data"))),
        }
        _, _, loss_sharded = jax.jit(step)(params_s, opt_state_s, batch_s, jax.random.PRNGKey(1))
        l2, g2 = jax.jit(loss_and_grads)(params_s, batch_s)

    np.testing.assert_allclose(float(loss_single), float(loss_sharded), rtol=1e-5)
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)
    # gradients agree across the two paths (params themselves diverge after
    # one adamw step because g/(|g|+eps) amplifies fp reassociation noise)
    for a, b in zip(jax.tree_util.tree_leaves(g1), jax.tree_util.tree_leaves(g2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


def test_cross_entropy_multilabel():
    logits = jnp.zeros((2, 3))
    labels = jnp.asarray([[1.0, 0.0, 1.0], [0.0, 1.0, 0.0]])
    loss = cross_entropy_loss(logits, labels, task="multi_label")
    np.testing.assert_allclose(float(loss), np.log(2), rtol=1e-5)
