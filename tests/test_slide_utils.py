"""find_level_for_target_mpp with a fake openslide handle (no C library)."""

from gigapath_tpu.data.slide_utils import find_level_for_target_mpp, get_slide_mpp


class FakeSlide:
    def __init__(self, props, downsamples):
        self.properties = props
        self.level_downsamples = downsamples
        self.level_count = len(downsamples)


def test_finds_matching_level():
    s = FakeSlide(
        {"tiff.XResolution": "40000", "tiff.YResolution": "40000", "tiff.ResolutionUnit": "centimeter"},
        [1.0, 2.0, 4.0],
    )  # base mpp 0.25 -> level 1 = 0.5
    assert get_slide_mpp(s) == (0.25, 0.25)
    assert find_level_for_target_mpp(s, 0.5) == 1


def test_openslide_mpp_property_preferred():
    s = FakeSlide({"openslide.mpp-x": "0.5", "openslide.mpp-y": "0.5"}, [1.0])
    assert find_level_for_target_mpp(s, 0.5) == 0


def test_anisotropic_slide_rejected():
    s = FakeSlide(
        {"openslide.mpp-x": "0.5", "openslide.mpp-y": "0.7"}, [1.0, 2.0]
    )  # Y axis never within tolerance -> None (parity: reference requires both)
    assert find_level_for_target_mpp(s, 0.5) is None


def test_missing_metadata():
    assert find_level_for_target_mpp(FakeSlide({}, [1.0]), 0.5) is None
    s = FakeSlide(
        {"tiff.XResolution": "40000", "tiff.YResolution": "40000", "tiff.ResolutionUnit": "inch"},
        [1.0],
    )
    assert find_level_for_target_mpp(s, 0.5) is None
