import numpy as np
import pytest

from gigapath_tpu.data.tiling import (
    assemble_tiles_2d,
    get_1d_padding,
    pad_for_tiling_2d,
    tile_array_2d,
)


@pytest.mark.parametrize("length,tile,expected", [(10, 5, (0, 0)), (11, 5, (2, 2)), (13, 5, (1, 1)), (1, 4, (1, 2))])
def test_get_1d_padding(length, tile, expected):
    assert get_1d_padding(length, tile) == expected


@pytest.mark.parametrize("channels_first", [True, False])
def test_pad_for_tiling(channels_first):
    img = np.arange(3 * 5 * 7).reshape((3, 5, 7) if channels_first else (5, 7, 3))
    padded, offset = pad_for_tiling_2d(img, 4, channels_first, constant_values=0)
    shape = padded.shape[1:] if channels_first else padded.shape[:2]
    assert shape == (8, 8)
    assert offset.tolist() == [0, 1]  # (x_off, y_off): w 7->8 pad (0,1), h 5->8 pad (1,2)


@pytest.mark.parametrize("channels_first", [True, False])
def test_tile_roundtrip(channels_first):
    rng = np.random.default_rng(1)
    img = rng.normal(size=(3, 8, 12) if channels_first else (8, 12, 3))
    tiles, coords = tile_array_2d(img, 4, channels_first)
    assert tiles.shape[0] == (8 // 4) * (12 // 4)
    assert coords.shape == (tiles.shape[0], 2)
    assembled, offset = assemble_tiles_2d(tiles, coords, fill_value=0.0, channels_first=channels_first)
    np.testing.assert_allclose(assembled, img)


def test_tile_coords_negative_when_padded():
    img = np.zeros((1, 5, 5))
    tiles, coords = tile_array_2d(img, 4, True, constant_values=0)
    assert tiles.shape == (4, 1, 4, 4)
    assert coords.min() < 0  # padding shifts the first tile into negative coords
