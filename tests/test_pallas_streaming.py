"""Pallas streaming-fold tier (ops/pallas_streaming.py) + its plan
integration.

The contracts this file pins (ISSUE 20 acceptance):

- interpret-mode parity of the Pallas ``pair_partial`` against the jnp
  oracle (``ops/streaming_prefill.pair_partial_attention``): forward
  1e-5 / grads 1e-4, including ragged ``valid_len`` tails, uneven
  head/ratio splits, and fully-masked pairs (sentinel discipline: both
  tiers' masked-row lse weighs to exactly zero downstream, but the raw
  sentinels differ — ~NEG_INF for the oracle, ~-7e19 for the kernel's
  underflow — so row comparisons gate on coverage);
- out-of-order chunk delivery is BIT-exact vs in-order under the Pallas
  path, including the bf16 fused result (deterministic fold sequence);
- flag/plan on-vs-off produce DISTINCT jit cache keys (flags ride the
  fold executable as a static arg);
- empty plan registry + zero env flags -> the plan-resolved fold traces
  the byte-identical program the pre-plan jnp path traces;
- the streaming session resolves its fold plan ONCE at construction —
  never per chunk or per fold.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from gigapath_tpu.models.slide_encoder import LongNetViT
from gigapath_tpu.ops.attention import NEG_INF
from gigapath_tpu.ops.pallas_dilated import (
    FLAG_ENV,
    PipelineFlags,
    snapshot_flags,
)
from gigapath_tpu.ops.pallas_streaming import (
    DEFAULT_FOLD_BLOCK,
    fold_blocks,
    pallas_pair_partial,
)
from gigapath_tpu.ops.streaming_prefill import (
    StreamingPrefillState,
    chunk_bounds,
    fold_pair,
    pair_partial_attention,
    streaming_dilated_attention,
)
from gigapath_tpu.plan import (
    ExecutionPlan,
    bless_plan,
    plan_stats,
    reset_plan_state,
    resolve_plan,
)

PALLAS = PipelineFlags(fold_pallas=True)

# covered-row threshold: a real lse is O(logits) ~ O(10); both tiers'
# fully-masked sentinels sit far below NEG_INF/2 (the same finite check
# StreamingPrefillState.lse_spread uses)
_COVERED = NEG_INF * 0.5


@pytest.fixture
def clean_env(monkeypatch, tmp_path):
    """Zero kernel env flags + a private registry path (mirrors
    tests/test_plan.py — the fold plan tests must never see a real
    registry or a user's env flags)."""
    for name in list(FLAG_ENV.values()) + ["GIGAPATH_PLAN"]:
        monkeypatch.delenv(name, raising=False)
    registry = str(tmp_path / "PLAN_REGISTRY.json")
    monkeypatch.setenv("GIGAPATH_PLAN_REGISTRY", registry)
    reset_plan_state()
    yield registry
    reset_plan_state()


def _blk(rng, B, c, H, Dh, dtype=jnp.float32):
    return jnp.asarray(rng.normal(size=(B, c, H, Dh)), dtype)


# one row per mask regime: local/in-segment, offset chunks, uneven
# H % r, ragged valid tail crossing the key chunk, ragged cq != ck,
# and a fully-masked pair (disjoint segments)
PAIR_CASES = [
    # (g, r, q0, k0, cq, ck, valid, H)
    (64, 1, 0, 0, 64, 64, None, 4),
    (128, 2, 64, 0, 64, 64, 100, 4),
    (64, 2, 0, 64, 64, 64, None, 6),
    (128, 4, 128, 0, 48, 64, 150, 4),
    (64, 1, 0, 64, 64, 64, None, 4),
]


class TestPairPartialParity:
    @pytest.mark.parametrize("g,r,q0,k0,cq,ck,valid,H", PAIR_CASES)
    def test_forward_matches_jnp_oracle(self, g, r, q0, k0, cq, ck,
                                        valid, H):
        rng = np.random.default_rng(0)
        q = _blk(rng, 1, cq, H, 8)
        k = _blk(rng, 1, ck, H, 8)
        v = _blk(rng, 1, ck, H, 8)
        o_ref, l_ref = pair_partial_attention(
            q, k, v, jnp.int32(q0), jnp.int32(k0),
            segment_len=g, ratio=r, valid_len=valid,
        )
        o_pl, l_pl = pallas_pair_partial(
            q, k, v, jnp.int32(q0), jnp.int32(k0),
            segment_len=g, ratio=r, valid_len=valid, interpret=True,
        )
        covered = np.asarray(l_ref) > _COVERED
        np.testing.assert_allclose(
            np.asarray(o_pl), np.asarray(o_ref), atol=1e-5, rtol=0,
        )
        np.testing.assert_allclose(
            np.asarray(l_pl)[covered], np.asarray(l_ref)[covered],
            atol=1e-5, rtol=0,
        )
        # uncovered rows: the kernel's sentinel must still weigh to
        # zero in any downstream combine — i.e. sit far below any lse
        assert (np.asarray(l_pl)[~covered] < _COVERED).all()
        # and the oracle's own covered set must agree with the kernel's
        assert ((np.asarray(l_pl) > _COVERED) == covered).all()

    # fwd covers every mask regime; grads re-check the three that
    # exercise distinct VJP paths (local, offset+ragged valid, ragged
    # cq) — each grad case re-traces both tiers, so keep the set lean
    @pytest.mark.parametrize(
        "g,r,q0,k0,cq,ck,valid,H",
        [PAIR_CASES[0], PAIR_CASES[1], PAIR_CASES[3]],
    )
    def test_grads_match_jnp_oracle(self, g, r, q0, k0, cq, ck, valid, H):
        """Grad parity THROUGH the fold step (combine_partials
        differentiates through the pair lse, so the dlse cotangent path
        of the custom VJP is exercised, not just do)."""
        rng = np.random.default_rng(1)
        q = _blk(rng, 1, cq, H, 8)
        k = _blk(rng, 1, ck, H, 8)
        v = _blk(rng, 1, ck, H, 8)
        acc_o = _blk(rng, 1, cq, H, 8) * 0.1
        acc_l = jnp.asarray(
            rng.normal(size=(1, H, cq)), jnp.float32
        )  # a live accumulator: every fold output row is covered

        def loss(flags):
            def f(q_, k_, v_):
                o, l = fold_pair(
                    acc_o, acc_l, q_, k_, v_,
                    jnp.int32(q0), jnp.int32(k0),
                    jnp.int32(valid if valid is not None else q0 + k0 + 512),
                    segment_len=g, ratio=r, flags=flags,
                )
                return (o.astype(jnp.float32) ** 2).sum() + (l ** 2).sum()

            return jax.grad(f, argnums=(0, 1, 2))(q, k, v)

        g_ref = loss(None)
        g_pl = loss(PALLAS)
        for name, a, b in zip("qkv", g_ref, g_pl):
            np.testing.assert_allclose(
                np.asarray(b), np.asarray(a), atol=1e-4, rtol=0,
                err_msg=f"d{name}",
            )

    def test_streaming_fused_parity_with_ragged_tail(self):
        """End-to-end through streaming_dilated_attention: the Pallas
        tier's fused chunk outputs match the jnp path at 1e-5 with a
        ragged valid_len tail masking the final chunk."""
        rng = np.random.default_rng(2)
        L, C, H, Dh = 256, 64, 4, 8
        bounds = chunk_bounds(L, C)
        blocks = [
            tuple(_blk(rng, 1, b - a, H, Dh) for _ in range(3))
            for a, b in bounds
        ]
        qb, kb, vb = (list(t) for t in zip(*blocks))
        kwargs = dict(
            bounds=bounds, segment_lengths=[64, 128],
            dilated_ratios=[1, 2], valid_len=230,
        )
        ref = streaming_dilated_attention(qb, kb, vb, **kwargs)
        got = streaming_dilated_attention(qb, kb, vb, flags=PALLAS,
                                          **kwargs)
        for i, (a, b) in enumerate(zip(ref, got)):
            np.testing.assert_allclose(
                np.asarray(b), np.asarray(a), atol=1e-5, rtol=0,
                err_msg=f"chunk {i}",
            )


class TestDeterminism:
    def _run(self, order, blocks, bounds, dtype):
        """Deliver chunks in ``order`` through a frontier buffer (the
        session's OOO discipline) into a Pallas-flagged fold state."""
        state = StreamingPrefillState(
            bounds, [64, 128], [1, 2], valid_len=230, flags=PALLAS,
        )
        held, nxt = {}, 0
        for i in order:
            held[i] = blocks[i]
            while nxt in held:
                state.ingest(nxt, *held.pop(nxt))
                nxt += 1
        assert nxt == len(bounds)
        return [np.asarray(o) for o in state.finalize()]

    @pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
    def test_out_of_order_arrival_is_bit_exact(self, dtype):
        """The fused result — including bf16 — is a pure function of
        the slide geometry, not delivery order: the frontier buffer
        replays the identical fold sequence, and the Pallas kernels are
        deterministic, so equality is BIT-exact, not approximate."""
        rng = np.random.default_rng(3)
        bounds = chunk_bounds(256, 64)
        blocks = [
            tuple(_blk(rng, 1, b - a, 4, 8, dtype) for _ in range(3))
            for a, b in bounds
        ]
        base = self._run(range(len(bounds)), blocks, bounds, dtype)
        ooo = self._run([2, 0, 3, 1], blocks, bounds, dtype)
        # finalize fuses in fp32 regardless of input dtype (the fold
        # accumulator discipline) — the bf16 case pins that bf16 INPUT
        # streams still land on one bit pattern per geometry
        assert base[0].dtype == np.float32
        for i, (a, b) in enumerate(zip(base, ooo)):
            assert np.array_equal(a, b), f"chunk {i} not bit-exact"


class TestPlanIntegration:
    def test_fold_blocks_precedence(self):
        # per-branch-class entry > scalar flag > module default
        flags = PipelineFlags(
            fold_pallas=True, fold_block_q=512,
            fold_branches=((2048, 2, 256, 128), (1024, 1, 0, 384)),
        )
        assert fold_blocks(flags, 2048, 2) == (256, 128)
        # zero entry fields fall through to the scalar flag / default
        assert fold_blocks(flags, 1024, 1) == (512, 384)
        # no matching entry: scalar flag, then default
        assert fold_blocks(flags, 4096, 4) == (512, DEFAULT_FOLD_BLOCK)
        assert fold_blocks(PipelineFlags(), 64, 1) == (
            DEFAULT_FOLD_BLOCK, DEFAULT_FOLD_BLOCK,
        )

    def test_flag_on_vs_off_distinct_jit_keys(self, clean_env):
        rng = np.random.default_rng(4)
        q = _blk(rng, 1, 64, 4, 8)
        acc_o = jnp.zeros((1, 64, 4, 8), jnp.float32)
        acc_l = jnp.full((1, 4, 64), NEG_INF, jnp.float32)
        jfold = jax.jit(
            fold_pair, static_argnames=("segment_len", "ratio", "flags")
        )
        args = (acc_o, acc_l, q, q, q,
                jnp.int32(0), jnp.int32(0), jnp.int32(64))
        jfold(*args, segment_len=64, ratio=1, flags=None)
        jfold(*args, segment_len=64, ratio=1, flags=None)
        base = jfold._cache_size()
        jfold(*args, segment_len=64, ratio=1, flags=PALLAS)
        assert jfold._cache_size() > base  # the DISTINCT key
        grown = jfold._cache_size()
        # replays of either static value hit their existing entries
        jfold(*args, segment_len=64, ratio=1, flags=None)
        jfold(*args, segment_len=64, ratio=1, flags=PALLAS)
        assert jfold._cache_size() == grown

        # ... and a BLESSED plan alone (zero env flags) re-keys too
        bless_plan(
            "stream_fold|x", ExecutionPlan(fold_pallas=True).as_dict(),
            path=clean_env,
        )
        reset_plan_state()
        resolved = resolve_plan(
            "stream_fold",
            (jax.ShapeDtypeStruct(q.shape, q.dtype),) * 3,
        )
        # wrong geometry key on purpose -> no hit -> default flags
        assert resolved == snapshot_flags()

    def test_empty_registry_is_byte_identical_to_jnp_path(self, clean_env):
        """The parity-oracle guarantee: with an empty registry and no
        env flags, plan-resolved dispatch traces the very program the
        pre-plan jnp fold traces — compared as jaxpr text, not
        numerics."""
        rng = np.random.default_rng(5)
        q = _blk(rng, 1, 64, 4, 8)
        acc_o = jnp.zeros((1, 64, 4, 8), jnp.float32)
        acc_l = jnp.full((1, 4, 64), NEG_INF, jnp.float32)
        resolved = resolve_plan(
            "stream_fold", (jax.ShapeDtypeStruct(q.shape, q.dtype),) * 3
        )
        assert resolved == PipelineFlags()

        def trace(flags):
            return str(jax.make_jaxpr(
                lambda *a: fold_pair(*a, segment_len=64, ratio=1,
                                     flags=flags)
            )(acc_o, acc_l, q, q, q,
              jnp.int32(0), jnp.int32(0), jnp.int32(64)))

        assert trace(None) == trace(resolved)

    def test_session_resolves_plan_once(self, clean_env):
        """The satellite pin: ONE resolve_plan per session construction
        — feeding every chunk and finalizing adds zero lookups."""
        rng = np.random.default_rng(6)
        model = LongNetViT(
            in_chans=16, embed_dim=32, depth=1, slide_ngrids=100,
            segment_length=[16, 32], dilated_ratio="[1, 2]",
            dropout=0.0, drop_path_rate=0.0,
        )
        from gigapath_tpu.models.streaming_encoder import (
            StreamingEncoderSession,
        )

        n = 24
        x = jnp.asarray(rng.normal(size=(1, n, 16)), jnp.float32)
        coords = jnp.asarray(
            rng.uniform(0, 100 * 256, (1, n, 2)), jnp.float32
        )
        params = model.init(jax.random.PRNGKey(0), x, coords)["params"]
        reset_plan_state()  # init ran the dense path's own resolves
        session = StreamingEncoderSession(model, params, n, chunk_tiles=8)
        stats = plan_stats()
        assert stats["lookups"] == 1, stats
        assert session.fold_flags == PipelineFlags()
        xn, cn = np.asarray(x[0]), np.asarray(coords[0])
        for i, (a, b) in enumerate(session.tile_bounds):
            session.feed(i, xn[a:b], cn[a:b])
        session.finalize()
        assert plan_stats()["lookups"] == 1  # still the ONE resolve
