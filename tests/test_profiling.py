"""Profiling hooks: trace capture, MoE telemetry extraction, cost analysis."""

import glob
import os

import jax
import jax.numpy as jnp
import numpy as np

from gigapath_tpu.utils.profiling import (
    annotate,
    collect_moe_metadata,
    compiled_flops,
    compiled_memory,
    trace,
)


def test_trace_writes_artifacts(tmp_path):
    with trace(str(tmp_path)):
        with annotate("matmul"):
            x = jnp.ones((64, 64))
            (x @ x).block_until_ready()
    # jax writes plugin event files under the log dir
    files = glob.glob(os.path.join(str(tmp_path), "**", "*"), recursive=True)
    assert any("trace" in f or "xplane" in f for f in files)


def test_collect_moe_metadata(rng):
    from gigapath_tpu.ops.moe.moe_layer import MOELayer

    layer = MOELayer(embed_dim=16, ffn_dim=32, num_experts=4, top1=True)
    x = jnp.asarray(rng.normal(size=(1, 8, 16)), jnp.float32)
    params = layer.init(jax.random.PRNGKey(0), x)["params"]
    _, mods = layer.apply({"params": params}, x, mutable=["intermediates"])
    meta = collect_moe_metadata(mods["intermediates"])
    assert any(k.endswith("entropy_gating") for k in meta)
    assert any("unused_expert1_count" in k for k in meta)
    assert all(np.isfinite(v) for v in meta.values())


def test_cost_analysis():
    def fn(x):
        return (x @ x).sum()

    x = jnp.ones((32, 32))
    flops = compiled_flops(fn, x)
    assert flops is None or flops > 0
    mem = compiled_memory(fn, x)
    assert mem is None or "argument_bytes" in mem


# ---------------------------------------------------------------------------
# edge cases: telemetry must never take a run down (ISSUE 2 satellite)
# ---------------------------------------------------------------------------

def test_collect_moe_metadata_empty_intermediates():
    assert collect_moe_metadata({}) == {}
    # intermediates without any sown moe_metadata: nothing matches
    assert collect_moe_metadata({"layer_0": {"other": (jnp.ones(()),)}}) == {}


def test_collect_moe_metadata_skips_non_scalar_leaves():
    """A non-scalar leaf under moe_metadata (unexpected by design) is
    skipped, not crashed on and not silently reduced to a fake scalar."""
    inter = {
        "moe": {
            "moe_metadata": (
                {
                    "entropy_gating": jnp.float32(0.7),
                    "bogus_vector": jnp.ones((4,)),  # non-scalar
                    "shaped_scalar": jnp.ones((1, 1)),  # size 1: still fine
                },
            )
        }
    }
    meta = collect_moe_metadata(inter)
    assert meta["moe/entropy_gating"] == np.float32(0.7)
    assert meta["moe/shaped_scalar"] == 1.0
    assert not any("bogus_vector" in k for k in meta)


def test_cost_analysis_returns_none_when_unavailable(monkeypatch):
    """compiled_flops/compiled_memory degrade to None when XLA cost
    analysis is unavailable (bench.py's analytic-fallback trigger)."""

    def broken_jit(fn):
        raise RuntimeError("cost analysis unavailable on this backend")

    monkeypatch.setattr(jax, "jit", broken_jit)
    assert compiled_flops(lambda x: x, jnp.ones(())) is None
    assert compiled_memory(lambda x: x, jnp.ones(())) is None


def test_cost_analysis_none_on_unloweratable_input():
    # a non-array argument fails at lower time -> swallowed into None
    assert compiled_flops(lambda x: x, object()) is None
    assert compiled_memory(lambda x: x, object()) is None
