"""bench.py's failure-degradation contract (the BENCH_r03/r04 lesson).

Two consecutive rounds lost their driver-verified perf record to single
unguarded backend-init failures; round 5 saw the third failure mode — an
indefinite HANG inside jax.devices(). These tests pin the hardened
behavior: bounded hang-proof probes, exit 0 with exactly one contractual
JSON line on stdout, and stale-snapshot degradation.
"""

import io
import json
import os
import sys
import unittest.mock as mock

import pytest

import bench


@pytest.fixture(autouse=True)
def _obs_stream_in_tmp(tmp_path, monkeypatch):
    # bench.main() appends telemetry to the repo-root BENCH_OBS.jsonl and
    # writes the perf ledger to BENCH_LEDGER.json; tests must not pollute
    # the committed provenance artifacts
    monkeypatch.setattr(bench, "OBS_STREAM", str(tmp_path / "BENCH_OBS.jsonl"))
    monkeypatch.setattr(bench, "BENCH_LEDGER", str(tmp_path / "BENCH_LEDGER.json"))


@pytest.fixture
def no_snapshot(tmp_path, monkeypatch):
    monkeypatch.setattr(bench, "LOCAL_SNAPSHOT", str(tmp_path / "BENCH_LOCAL.json"))
    return tmp_path


def _run_main_failing(capsys):
    with mock.patch.object(
        bench, "_probe_backend_subprocess", return_value=(False, "probe hung")
    ), mock.patch.object(bench.time, "sleep"):
        bench.main()
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 1, f"stdout must be exactly one JSON line, got {out}"
    return json.loads(out[0])


def test_probe_timeout_is_bounded():
    """A hung backend init must be killed by the subprocess timeout, not
    block forever (the round-5 tunnel failure mode)."""
    import subprocess

    def hang(cmd, capture_output, text, timeout):
        raise subprocess.TimeoutExpired(cmd, timeout)

    with mock.patch("subprocess.run", hang):
        ok, msg = bench._probe_backend_subprocess(1.0)
    assert not ok
    assert "hung" in msg


def test_acquire_backend_raises_after_bounded_attempts():
    calls = []
    with mock.patch.object(
        bench, "_probe_backend_subprocess",
        side_effect=lambda t: calls.append(t) or (False, "down"),
    ):
        with pytest.raises(RuntimeError, match="backend unavailable"):
            bench.acquire_backend(attempts=3, delays=(0,), probe_timeout=1.0)
    assert len(calls) == 3


def test_failure_emits_contractual_json_without_snapshot(no_snapshot, capsys):
    payload = _run_main_failing(capsys)
    assert payload["metric"] == "slide_embed_tokens_per_sec"
    assert payload["value"] is None
    assert payload["unit"] == "tokens/s"
    assert "error" in payload
    assert "stale" not in payload
    assert "last_good" not in payload
    # an unmeasured round has no compiled-artifact profile to point at:
    # the ledger fields must not leak into the failure payload
    assert "ledger" not in payload
    assert "compiled_flops" not in payload
    assert not os.path.exists(bench.BENCH_LEDGER)


def test_failure_reports_snapshot_only_as_last_good(no_snapshot, capsys):
    """The round-5 advisor contract: an unmeasured round must never be
    recordable as fresh. On failure 'value' stays null even when a
    snapshot exists; the old number appears ONLY under last_good_*,
    alongside stale=true and the error."""
    snap = {
        "metric": "slide_embed_tokens_per_sec",
        "value": 138400.0,
        "unit": "tokens/s",
        "vs_baseline": 0.373,
        "snapshot_utc": "2026-07-30T23:00:00Z",
    }
    with open(bench.LOCAL_SNAPSHOT, "w") as f:
        json.dump(snap, f)
    payload = _run_main_failing(capsys)
    assert payload["value"] is None, (
        "failure must not launder the stale snapshot into 'value'"
    )
    assert "vs_baseline" not in payload  # stale metrics stay out of top level
    assert payload["stale"] is True
    assert payload["last_good_value"] == 138400.0
    assert payload["last_good_snapshot_utc"] == "2026-07-30T23:00:00Z"
    assert payload["last_good"]["vs_baseline"] == 0.373
    assert "error" in payload


def test_failure_strips_error_and_stale_from_last_good(no_snapshot, capsys):
    """A snapshot that (from an older bench version) carries error/stale
    keys must not re-surface them inside last_good."""
    snap = {
        "metric": "slide_embed_tokens_per_sec",
        "value": 99.0,
        "unit": "tokens/s",
        "error": "old error",
        "stale": True,
        "snapshot_utc": "2026-07-29T00:00:00Z",
    }
    with open(bench.LOCAL_SNAPSHOT, "w") as f:
        json.dump(snap, f)
    payload = _run_main_failing(capsys)
    assert payload["value"] is None
    assert "error" not in payload["last_good"]
    assert "stale" not in payload["last_good"]
    assert payload["last_good_value"] == 99.0


def test_success_embeds_ledger_and_headline_profile_fields(
    no_snapshot, capsys, monkeypatch
):
    """ISSUE 4 satellite: the success JSON line carries the ledger path
    plus headline compiled-FLOPs / peak-HBM fields WITHOUT breaking the
    one-line-stdout contract."""

    def fake_run_bench(runlog=None, ledger=None):
        # what run_bench returns after ledgering the slide forward
        return {
            "metric": "slide_embed_tokens_per_sec",
            "value": 138400.0,
            "unit": "tokens/s",
            "peak_hbm_gb": 0.63,
            "compiled_flops": 3.0e12,
            "ledger": ledger.path if ledger is not None else None,
        }

    monkeypatch.setattr(bench, "run_bench", fake_run_bench)
    bench.main()
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 1, f"stdout must be exactly one JSON line, got {out}"
    payload = json.loads(out[0])
    assert payload["value"] == 138400.0
    assert payload["compiled_flops"] == 3.0e12
    assert payload["peak_hbm_gb"] == 0.63
    assert payload["ledger"] == bench.BENCH_LEDGER
    # the snapshot carries the same provenance fields
    with open(bench.LOCAL_SNAPSHOT) as f:
        snap = json.load(f)
    assert snap["ledger"] == bench.BENCH_LEDGER
    assert snap["compiled_flops"] == 3.0e12


def test_success_memoizes_backend(monkeypatch):
    """After one successful acquire, later calls (chip_peak_flops) must not
    spawn further subprocess probes — a second probe is one extra roll of
    the flaky-tunnel dice per bench run."""
    monkeypatch.setattr(bench, "_BACKEND_READY", False)
    probes = []
    with mock.patch.object(
        bench, "_probe_backend_subprocess",
        side_effect=lambda t: probes.append(t) or (True, "cpu"),
    ):
        bench.acquire_backend(probe_timeout=1.0)
        bench.acquire_backend(probe_timeout=1.0)
    assert len(probes) == 1
