"""Encoder stack, configs, LongNet registry."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gigapath_tpu.architecture.config import EncoderConfig
from gigapath_tpu.architecture.encoder import Encoder
from gigapath_tpu.architecture.init import apply_init_scaling, subln_init_scale
from gigapath_tpu.models import longnet_config
from gigapath_tpu.models.longnet import make_longnet_from_name


def test_config_parsing_and_invariants():
    cfg = EncoderConfig(segment_length="[512, 1024]", dilated_ratio="[1, 2]")
    assert cfg.segment_length == [512, 1024]
    assert cfg.dilated_ratio == [1, 2]
    assert cfg.subln and cfg.encoder_normalize_before and not cfg.deepnorm

    cfg2 = EncoderConfig(deepnorm=True, subln=False)
    assert not cfg2.encoder_normalize_before and not cfg2.subln


def test_config_rejects_code_injection():
    with pytest.raises((ValueError, SyntaxError)):
        EncoderConfig(segment_length="__import__('os').getcwd()")


def test_config_from_dict_tolerates_registry_extras():
    cfg = EncoderConfig.from_dict(longnet_config.get_config("LongNet_test"))
    assert cfg.encoder_layers == 1
    assert "block_shift" in cfg.extras  # dead key, tolerated like the reference


def test_registry_has_all_reference_configs():
    names = longnet_config.list_configs()
    assert len(names) == 22
    assert "LongNet_12_layers_768_dim" in names
    c = longnet_config.get_config("LongNet_12_layers_768_dim")
    assert c["encoder_ffn_embed_dim"] == 3072 and c["encoder_attention_heads"] == 16
    v = longnet_config.get_config("LongNet_Vanilla_12_layers_256_dim")
    assert v["segment_length"] == "[10000000]" and v["encoder_attention_heads"] == 8


def test_plain_encoder_forward(rng):
    cfg = EncoderConfig(
        encoder_layers=2, encoder_embed_dim=32, encoder_ffn_embed_dim=64,
        encoder_attention_heads=4, dropout=0.0,
    )
    enc = Encoder(args=cfg)
    x = jnp.asarray(rng.normal(size=(2, 10, 32)), jnp.float32)
    params = enc.init(jax.random.PRNGKey(0), token_embeddings=x)
    out = enc.apply(params, token_embeddings=x, return_all_hiddens=True)
    assert out["encoder_out"].shape == (2, 10, 32)
    assert len(out["encoder_states"]) == 3  # input + 2 layers
    assert len(out["l_aux"]) == 2


def test_padding_mask_zeroes_inputs(rng):
    cfg = EncoderConfig(
        encoder_layers=1, encoder_embed_dim=16, encoder_ffn_embed_dim=32,
        encoder_attention_heads=2,
    )
    enc = Encoder(args=cfg)
    x = jnp.asarray(rng.normal(size=(1, 6, 16)), jnp.float32)
    mask = jnp.array([[False, False, False, False, True, True]])
    params = enc.init(jax.random.PRNGKey(0), token_embeddings=x)
    out_masked = enc.apply(params, token_embeddings=x, encoder_padding_mask=mask)
    x_zeroed = x.at[:, 4:].set(0.0)
    out_zeroed = enc.apply(params, token_embeddings=x_zeroed, encoder_padding_mask=mask)
    np.testing.assert_allclose(
        np.asarray(out_masked["encoder_out"]), np.asarray(out_zeroed["encoder_out"]), atol=1e-5
    )


def test_longnet_from_name_small(rng):
    enc, cfg = make_longnet_from_name("LongNet_test", dropout=0.0, drop_path_rate=0.0)
    assert cfg.encoder_layers == 1 and cfg.encoder_embed_dim == 192
    x = jnp.asarray(rng.normal(size=(1, 20, 192)), jnp.float32)
    params = enc.init(jax.random.PRNGKey(0), token_embeddings=x)
    out = enc.apply(params, token_embeddings=x)
    assert out["encoder_out"].shape == (1, 20, 192)
    assert np.isfinite(np.asarray(out["encoder_out"])).all()


def test_longnet_remat_matches_plain(rng):
    x = jnp.asarray(rng.normal(size=(1, 12, 192)), jnp.float32)
    enc, _ = make_longnet_from_name("LongNet_test", dropout=0.0, drop_path_rate=0.0)
    params = enc.init(jax.random.PRNGKey(0), token_embeddings=x)
    out = enc.apply(params, token_embeddings=x)["encoder_out"]
    enc_ckpt, _ = make_longnet_from_name(
        "LongNet_test", dropout=0.0, drop_path_rate=0.0, checkpoint_activations=True
    )
    out_ckpt = enc_ckpt.apply(params, token_embeddings=x)["encoder_out"]
    np.testing.assert_allclose(np.asarray(out), np.asarray(out_ckpt), atol=1e-5)


def test_remat_with_dropout_traces(rng):
    """checkpoint_activations + dropout>0 must not hit TracerBoolConversion
    (deterministic is a static arg under nn.remat)."""
    enc, _ = make_longnet_from_name(
        "LongNet_test", dropout=0.3, drop_path_rate=0.1, checkpoint_activations=True
    )
    x = jnp.asarray(rng.normal(size=(1, 12, 192)), jnp.float32)
    params = enc.init(
        {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)},
        token_embeddings=x, deterministic=False,
    )
    out = enc.apply(
        params, token_embeddings=x, deterministic=False,
        rngs={"dropout": jax.random.PRNGKey(2)},
    )
    assert np.isfinite(np.asarray(out["encoder_out"])).all()


def test_dilated_attention_dropout_active(rng):
    """attention dropout in the dilated path changes outputs at train time."""
    from gigapath_tpu.ops.dilated_attention import DilatedAttention

    mod = DilatedAttention(
        embed_dim=32, num_heads=4, dropout=0.5,
        segment_length=(8,), dilated_ratio=(1,),
    )
    x = jnp.asarray(rng.normal(size=(1, 16, 32)), jnp.float32)
    params = mod.init(jax.random.PRNGKey(0), x, x, x)
    out_eval = mod.apply(params, x, x, x, deterministic=True)
    out_train = mod.apply(
        params, x, x, x, deterministic=False, rngs={"dropout": jax.random.PRNGKey(3)}
    )
    assert not np.allclose(np.asarray(out_eval), np.asarray(out_train))


def test_subln_init_scaling():
    params = {"layers_0": {"ffn": {"fc1": {"kernel": jnp.ones((2, 2)), "bias": jnp.ones(2)}},
                           "self_attn": {"q_proj": {"kernel": jnp.ones((2, 2))}}}}
    scaled = apply_init_scaling(params, subln=True, deepnorm=False, num_layers=12)
    s = subln_init_scale(12)
    np.testing.assert_allclose(scaled["layers_0"]["ffn"]["fc1"]["kernel"], s)
    np.testing.assert_allclose(scaled["layers_0"]["ffn"]["fc1"]["bias"], 1.0)  # bias untouched
    np.testing.assert_allclose(scaled["layers_0"]["self_attn"]["q_proj"]["kernel"], 1.0)
