"""Native C++ host kernels: build, ctypes binding, exact numpy parity.

The compute path's native story is Pallas (tests/test_pallas_flash.py);
this covers the host-runtime C++ (gigapath_tpu/native): tile normalization,
luminance occupancy, ragged padding — each against its numpy reference.
"""

import numpy as np
import pytest

from gigapath_tpu import native


def test_library_builds():
    """g++ is baked into this image; the .so must build and load."""
    assert native.available(), "native tile_ops failed to build"


def test_normalize_tiles_matches_numpy(rng):
    from gigapath_tpu.models.tile_encoder import IMAGENET_MEAN, IMAGENET_STD

    batch = rng.integers(0, 256, (4, 32, 32, 3)).astype(np.uint8)
    out = native.normalize_tiles(batch)
    ref = (
        (batch.astype(np.float32) / 255.0) - np.asarray(IMAGENET_MEAN, np.float32)
    ) / np.asarray(IMAGENET_STD, np.float32)
    np.testing.assert_allclose(out, ref, atol=1e-5)
    assert out.dtype == np.float32


def test_normalize_tiles_many_channels_falls_back(rng):
    """channels > 8 exceeds the C kernel's affine table; numpy path must
    kick in instead of reading past it."""
    batch = rng.integers(0, 256, (2, 4, 4, 9)).astype(np.uint8)
    mean = np.linspace(0.1, 0.9, 9)
    std = np.linspace(0.5, 1.5, 9)
    out = native.normalize_tiles(batch, mean, std)
    ref = ((batch.astype(np.float32) / 255.0) - mean.astype(np.float32)) / std.astype(
        np.float32
    )
    np.testing.assert_allclose(out, ref, atol=1e-5)


def test_normalize_custom_stats(rng):
    batch = rng.integers(0, 256, (2, 8, 8, 3)).astype(np.uint8)
    mean, std = [0.5, 0.5, 0.5], [0.25, 0.25, 0.25]
    out = native.normalize_tiles(batch, mean, std)
    ref = ((batch.astype(np.float32) / 255.0) - 0.5) / 0.25
    np.testing.assert_allclose(out, ref, atol=1e-5)


def test_luminance_occupancy_matches_numpy(rng):
    tiles = rng.integers(0, 256, (6, 3, 16, 16)).astype(np.uint8)
    threshold = 127.5
    out = native.luminance_occupancy(tiles, threshold)
    lum = tiles.astype(np.float32).mean(axis=1)
    ref = (lum < threshold).mean(axis=(-2, -1)).astype(np.float32)
    np.testing.assert_allclose(out, ref, atol=1e-6)


def test_pad_sequences_matches_numpy(rng):
    seqs = [
        rng.normal(size=(5, 8)).astype(np.float32),
        rng.normal(size=(9, 8)).astype(np.float32),
        rng.normal(size=(1, 8)).astype(np.float32),
    ]
    out = native.pad_sequences(seqs, max_len=9)
    assert out.shape == (3, 9, 8)
    np.testing.assert_array_equal(out[0, :5], seqs[0])
    np.testing.assert_array_equal(out[0, 5:], 0)
    np.testing.assert_array_equal(out[1], seqs[1])
    # truncation beyond max_len
    out2 = native.pad_sequences(seqs, max_len=4)
    np.testing.assert_array_equal(out2[1], seqs[1][:4])


def test_preprocess_tile_uses_native(rng):
    """Transform output through the native path equals the pure formula."""
    from gigapath_tpu.data.transforms import preprocess_tile

    img = rng.integers(0, 256, (64, 64, 3)).astype(np.uint8)
    out = preprocess_tile(img, crop_size=32)
    assert out.shape == (32, 32, 3) and out.dtype == np.float32
    assert np.isfinite(out).all()
