"""Pipeline API + predict/pretrain/inference drivers, end-to-end on tiny
synthetic slides and the smoke-test encoders.

Mirrors the reference user journey (``demo/run_gigapath.py`` -> §3.2 call
stack): tile a slide -> encode tiles -> encode slide; then the auxiliary
drivers: predict.py (checkpoint -> predictions.csv), the MAE + contrastive
pretrain stages, and the feature-file inference driver.
"""

import glob
import os

import jax
import jax.numpy as jnp
import numpy as np
import pandas as pd
import pytest
from PIL import Image

from gigapath_tpu.models.tile_encoder import VisionTransformer


def _tiny_tile_encoder():
    return VisionTransformer(
        img_size=32, patch_size=16, embed_dim=32, depth=1, num_heads=4,
        mlp_ratio=2.0,
    )


def _synthetic_slide_png(tmp_path, name="slide.png", size=256, seed=0):
    rng = np.random.default_rng(seed)
    arr = np.full((size, size, 3), 245, np.uint8)
    q = size // 4
    arr[q : 3 * q, q : 3 * q] = rng.integers(30, 120, (2 * q, 2 * q, 3))
    path = tmp_path / name
    Image.fromarray(arr).save(path)
    return str(path)


class TestPipeline:
    def test_tile_encode_slide_encode(self, tmp_path, rng):
        """The full §3.2 journey on synthetic data + tiny encoders."""
        from gigapath_tpu.pipeline import (
            run_inference_with_slide_encoder,
            run_inference_with_tile_encoder,
            tile_one_slide,
        )
        from gigapath_tpu.models import slide_encoder as slide_lib
        from gigapath_tpu.models.tile_encoder import init_params

        slide_path = _synthetic_slide_png(tmp_path)
        save_dir = tmp_path / "tiles"
        slide_dir = tile_one_slide(slide_path, str(save_dir), tile_size=64)
        tile_paths = sorted(glob.glob(os.path.join(slide_dir, "*.png")))
        assert len(tile_paths) > 0

        tile_model = _tiny_tile_encoder()
        tile_params = init_params(tile_model)
        out = run_inference_with_tile_encoder(
            tile_paths, tile_model, tile_params, batch_size=4
        )
        assert out["tile_embeds"].shape == (len(tile_paths), 32)
        assert out["coords"].shape == (len(tile_paths), 2)
        assert np.isfinite(out["tile_embeds"]).all()

        slide_model, slide_params = slide_lib.create_model(
            "", "gigapath_slide_enc_tiny", in_chans=32
        )
        embeds = run_inference_with_slide_encoder(
            out["tile_embeds"], out["coords"], slide_model, slide_params
        )
        assert "last_layer_embed" in embeds
        assert embeds["last_layer_embed"].shape == (1, 32)
        # all_layer_embed: depth+1 hidden states + final
        assert embeds["layer_0_embed"].shape == (1, 32)

    def test_tile_encoder_batch_padding(self, tmp_path, rng):
        """Partial last batch pads to the compiled shape and slices back."""
        from gigapath_tpu.data.transforms import preprocess_tile
        from gigapath_tpu.pipeline import run_inference_with_tile_encoder
        from gigapath_tpu.models.tile_encoder import init_params

        paths = []
        for i in range(5):  # 5 tiles, batch 4 -> one full + one partial
            arr = rng.integers(0, 255, (32, 32, 3)).astype(np.uint8)
            p = tmp_path / f"{i:05d}x_{i:05d}y.png"
            Image.fromarray(arr).save(p)
            paths.append(str(p))

        tile_model = _tiny_tile_encoder()
        tile_params = init_params(tile_model)

        # bypass resize-to-224: feed 32x32 directly
        import gigapath_tpu.pipeline as pipeline_mod

        orig = pipeline_mod.load_tile_encoder_transforms
        pipeline_mod.load_tile_encoder_transforms = lambda **kw: (
            lambda img: np.asarray(img, np.float32) / 255.0
        )
        try:
            out = run_inference_with_tile_encoder(
                paths, tile_model, tile_params, batch_size=4
            )
        finally:
            pipeline_mod.load_tile_encoder_transforms = orig
        assert out["tile_embeds"].shape == (5, 32)


class TestPredict:
    def test_predict_writes_csv(self, tmp_path, rng):
        import h5py

        from gigapath_tpu.finetune.predict import predict
        from gigapath_tpu.models.classification_head import get_model
        from gigapath_tpu.utils.checkpoint import save_checkpoint

        root = tmp_path / "h5_files"
        root.mkdir()
        rows = []
        for i in range(3):
            with h5py.File(root / f"s{i}.h5", "w") as f:
                f.create_dataset("features", data=rng.normal(size=(10, 16)).astype(np.float32))
                f.create_dataset("coords", data=rng.integers(0, 999, (10, 2)).astype(np.float32))
            rows.append({"slide_id": f"s{i}.svs", "pat_id": f"p{i}", "label": ["neg", "pos"][i % 2]})
        csv = tmp_path / "ds.csv"
        pd.DataFrame(rows).to_csv(csv, index=False)
        yaml_path = tmp_path / "task.yaml"
        yaml_path.write_text(
            "name: toy\nsetting: multi_class\nmodel_arch: gigapath_slide_enc_tiny\n"
            "label_dict:\n  neg: 0\n  pos: 1\nmax_tiles: 16\n"
        )

        _, params = get_model(
            input_dim=16, latent_dim=32, feat_layer="1", n_classes=2,
            model_arch="gigapath_slide_enc_tiny",
        )
        ckpt = tmp_path / "ckpt"
        save_checkpoint(str(ckpt), {"params": jax.device_get(params)})

        df = predict(
            str(ckpt), str(csv), str(root), str(yaml_path), str(tmp_path / "out"), "exp",
            argv=["--input_dim", "16", "--latent_dim", "32", "--feat_layer", "1",
                  "--dropout", "0.0", "--drop_path_rate", "0.0"],
        )
        assert len(df) == 3
        out_csv = tmp_path / "out" / "toy" / "exp" / "predictions" / "predictions.csv"
        assert out_csv.exists()
        probs = df["probabilities"].iloc[0]
        assert len(probs) == 2 and abs(sum(probs) - 1.0) < 1e-4


class TestPretrain:
    def test_random_masking_ratio(self, rng):
        from gigapath_tpu.pretrain.pretrain_gigapath import random_masking

        imgs = jnp.ones((2, 16, 16, 3))
        masked = random_masking(jax.random.PRNGKey(0), imgs, 0.75)
        frac_kept = float((masked[0, :, :, 0] > 0).mean())
        assert frac_kept == pytest.approx(0.25, abs=0.01)

    def test_mae_loss_decreases(self, tmp_path, rng):
        from gigapath_tpu.pretrain.pretrain_gigapath import pretrain_tile_encoder

        tiles_dir = tmp_path / "tiles"
        tiles_dir.mkdir()
        paths = []
        for i in range(8):
            arr = rng.integers(0, 255, (32, 32, 3)).astype(np.uint8)
            p = tiles_dir / f"{i:05d}x_00000y.png"
            Image.fromarray(arr).save(p)
            paths.append(str(p))
        best = pretrain_tile_encoder(
            paths,
            str(tmp_path / "out"),
            encoder=_tiny_tile_encoder(),
            batch_size=4,
            num_epochs=3,
            learning_rate=1e-3,
        )
        from gigapath_tpu.utils.checkpoint import restore_checkpoint

        state = restore_checkpoint(best)
        assert np.isfinite(state["loss"])

    def test_contrastive_loss_properties(self, rng):
        from gigapath_tpu.pretrain.pretrain_gigapath import contrastive_loss

        f = jnp.asarray(rng.normal(size=(4, 8)), jnp.float32)
        loss = contrastive_loss(f)
        assert float(loss) > 0
        # single sample -> the reference's 0.1 sentinel
        assert float(contrastive_loss(f[:1])) == pytest.approx(0.1)
        # orthogonal features at low temperature -> small loss
        eye = jnp.eye(4, 8)
        assert float(contrastive_loss(eye)) < float(contrastive_loss(jnp.ones((4, 8))))

    def test_slide_contrastive_stage(self, tmp_path, rng):
        from gigapath_tpu.pretrain.pretrain_gigapath import pretrain_slide_encoder
        from gigapath_tpu.models.tile_encoder import init_params

        slide_dirs = []
        for s in range(3):
            d = tmp_path / f"slide_{s}"
            d.mkdir()
            for i in range(4):
                arr = rng.integers(0, 255, (32, 32, 3)).astype(np.uint8)
                Image.fromarray(arr).save(d / f"{i:05d}x_00000y.png")
            slide_dirs.append(str(d))
        enc = _tiny_tile_encoder()
        params = init_params(enc)
        best = pretrain_slide_encoder(
            enc, params, slide_dirs, str(tmp_path / "out"), num_epochs=3
        )
        from gigapath_tpu.utils.checkpoint import restore_checkpoint

        assert np.isfinite(restore_checkpoint(best)["loss"])


class TestInferenceDriver:
    def test_feature_file_inference(self, tmp_path, rng, monkeypatch):
        """Default (bucketed serving) path vs the --no-buckets exact
        oracle: same CSV verdicts either way."""
        import torch

        from gigapath_tpu.inference import load_model, run_inference

        # small serving buckets so the tier-1 compile stays tiny
        monkeypatch.setenv("GIGAPATH_SERVE_BUCKET_MIN", "16")
        monkeypatch.setenv("GIGAPATH_SERVE_BUCKET_ALIGN", "16")
        torch.manual_seed(0)
        for i in range(3):
            torch.save(
                torch.randn(10, 16), tmp_path / f"slide{i}_features.pt"
            )
        model, params = load_model(
            "", input_dim=16, latent_dim=32, feat_layer="1", n_classes=2,
            model_arch="gigapath_slide_enc_tiny",
        )
        out_csv = tmp_path / "preds.csv"
        df = run_inference(model, params, str(tmp_path), str(out_csv),
                           batch_size=4)
        assert len(df) == 3
        assert set(df.columns) == {"slide_id", "predicted_label", "confidence"}
        assert ((df["confidence"] >= 0.0) & (df["confidence"] <= 1.0)).all()

        exact = run_inference(
            model, params, str(tmp_path), str(tmp_path / "exact.csv"),
            use_buckets=False,
        )
        assert list(exact["slide_id"]) == list(df["slide_id"])
        assert list(exact["predicted_label"]) == list(df["predicted_label"])
        # the model is bf16 (load_model's serving default): padded vs
        # exact shapes round differently at bf16 resolution; f32 parity
        # at 1e-5 is pinned in tests/test_serve.py
        np.testing.assert_allclose(
            exact["confidence"], df["confidence"], atol=5e-3
        )

    def test_streaming_route_matches_exact_path(self, tmp_path):
        """The --stream (chunked prefill) route vs the exact-shape
        oracle: same verdicts, confidences within f32 streaming
        tolerance (the model here is f32; load_model's bf16 serving
        default is exercised by the bucketed test above). Ragged final
        chunks included (10 tiles, chunk 4)."""
        import torch

        from gigapath_tpu.inference import run_inference
        from gigapath_tpu.models.classification_head import get_model

        torch.manual_seed(0)
        for i in range(3):
            torch.save(
                {"features": torch.randn(10, 16),
                 "coords": torch.rand(10, 2) * 5000},
                tmp_path / f"slide{i}_features.pt",
            )
        model, params = get_model(
            input_dim=16, latent_dim=32, feat_layer="1", n_classes=2,
            model_arch="gigapath_slide_enc_tiny", dtype=None,
        )
        exact = run_inference(
            model, params, str(tmp_path), str(tmp_path / "exact.csv"),
            use_buckets=False,
        )
        stream = run_inference(
            model, params, str(tmp_path), str(tmp_path / "stream.csv"),
            stream=True, stream_chunk=4, prefetch=2,
        )
        assert list(stream["slide_id"]) == list(exact["slide_id"])
        assert list(stream["predicted_label"]) == list(
            exact["predicted_label"]
        )
        np.testing.assert_allclose(
            stream["confidence"], exact["confidence"], atol=1e-5
        )

    def test_oversized_slide_falls_back_to_exact_shape(self, tmp_path,
                                                       monkeypatch):
        """A slide above the ladder's top rung must NOT abort the run:
        it routes through the exact-shape fallback while the rest of the
        batch serves bucketed."""
        import torch

        from gigapath_tpu.inference import load_model, run_inference

        monkeypatch.setenv("GIGAPATH_SERVE_BUCKET_MIN", "16")
        monkeypatch.setenv("GIGAPATH_SERVE_BUCKET_ALIGN", "16")
        monkeypatch.setenv("GIGAPATH_SERVE_BUCKET_MAX", "16")
        torch.manual_seed(0)
        torch.save(torch.randn(10, 16), tmp_path / "small_features.pt")
        torch.save(torch.randn(40, 16), tmp_path / "toobig_features.pt")
        model, params = load_model(
            "", input_dim=16, latent_dim=32, feat_layer="1", n_classes=2,
            model_arch="gigapath_slide_enc_tiny",
        )
        df = run_inference(model, params, str(tmp_path),
                           str(tmp_path / "preds.csv"), batch_size=2)
        assert sorted(df["slide_id"]) == ["small", "toobig"]

        exact = run_inference(
            model, params, str(tmp_path), str(tmp_path / "exact.csv"),
            use_buckets=False,
        )
        assert list(exact["predicted_label"]) == list(df["predicted_label"])
        np.testing.assert_allclose(
            exact["confidence"], df["confidence"], atol=5e-3
        )
