"""Typed metrics registry + SLO burn tracker (gigapath_tpu/obs/metrics.py).

The pinned invariants (ISSUE 9):

- **exactness**: concurrent observers drop nothing and double-count
  nothing — histogram/counter totals are exact under threaded writers;
- **atomic snapshot/merge**: one consistent cut; merges add bucket-wise
  and refuse mismatched ladders;
- **one percentile**: ``scripts/obs_report.py`` and the registry share
  the single nearest-rank implementation (GL012's fix);
- **zero overhead when off**: a NullRunLog (or ``GIGAPATH_METRICS=0``)
  yields the null registry — no events, no files;
- **SLO burn**: transition-edged both ways, multi-window, min-event
  floored — the contract the anomaly engine's ``slo_burn`` detector
  builds on.
"""

import json
import os
import sys
import threading

import pytest

from gigapath_tpu.obs import NullRunLog, RunLog
from gigapath_tpu.obs.metrics import (
    MetricsRegistry,
    NullMetricsRegistry,
    NullSloTracker,
    SloTracker,
    exponential_bounds,
    get_metrics,
    histogram_quantile,
    merge_snapshots,
    percentile,
    to_json_line,
    to_prometheus,
)

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                    "scripts"),
)


# ---------------------------------------------------------------------------
# instruments
# ---------------------------------------------------------------------------

class TestInstruments:
    def test_counter_gauge_basics(self):
        m = MetricsRegistry()
        c = m.counter("reqs")
        c.inc()
        c.inc(2.5)
        assert c.value == 3.5
        with pytest.raises(ValueError):
            c.inc(-1)
        g = m.gauge("depth")
        g.set(7)
        g.inc(-2)
        assert g.value == 5.0

    def test_instruments_are_create_once_by_name(self):
        m = MetricsRegistry()
        assert m.counter("x") is m.counter("x")
        assert m.histogram("h") is m.histogram("h")

    def test_type_collision_refused(self):
        m = MetricsRegistry()
        m.counter("x")
        with pytest.raises(ValueError, match="different type"):
            m.gauge("x")
        with pytest.raises(ValueError, match="different type"):
            m.histogram("x")

    def test_exponential_bounds_shape_and_validation(self):
        bounds = exponential_bounds(1e-3, 2.0, 5)
        assert bounds == [1e-3, 2e-3, 4e-3, 8e-3, 16e-3]
        with pytest.raises(ValueError):
            exponential_bounds(0, 2.0, 5)
        with pytest.raises(ValueError):
            exponential_bounds(1e-3, 1.0, 5)

    def test_histogram_counts_sum_min_max(self):
        m = MetricsRegistry()
        h = m.histogram("lat", bounds=[0.1, 1.0, 10.0])
        for v in (0.05, 0.5, 0.5, 5.0, 50.0):
            h.observe(v)
        assert h.count == 5
        assert h.counts == [1, 2, 1, 1]  # last slot = +inf overflow
        assert h.sum == pytest.approx(56.05)
        assert h.vmin == 0.05 and h.vmax == 50.0

    def test_histogram_nonfinite_observation_ignored(self):
        m = MetricsRegistry()
        h = m.histogram("lat")
        h.observe(float("nan"))
        h.observe(float("inf"))
        assert h.count == 0

    def test_empty_histogram_snapshot_is_strict_json(self, tmp_path):
        """A registered-but-never-observed histogram must flush None
        quantiles, not NaN — a bare NaN token in the run JSONL breaks
        the one-strict-JSON-object-per-line artifact contract."""
        log = RunLog(str(tmp_path / "run.jsonl"), driver="t", echo=False)
        m = get_metrics(log)
        m.histogram("serve.e2e_s")  # registered, zero observations
        log.run_end(status="ok")
        for line in open(log.path):
            ev = json.loads(line, parse_constant=lambda c: (_ for _ in ())
                            .throw(ValueError(f"non-strict token {c}")))
            if ev["kind"] == "metrics":
                h = ev["histograms"]["serve.e2e_s"]
                assert h["p50"] is None and h["p99"] is None
                assert h["count"] == 0

    def test_histogram_quantile_is_conservative_upper_bound(self):
        """The quantile answers the containing bucket's UPPER bound
        (over-estimate, never under), clamped to the observed max in
        the overflow bucket."""
        bounds = [0.1, 1.0, 10.0]
        # 10 observations all in the (0.1, 1.0] bucket
        assert histogram_quantile(bounds, [0, 10, 0, 0], 0.5) == 1.0
        # overflow bucket: clamp to vmax
        assert histogram_quantile(bounds, [0, 0, 0, 3], 0.99, vmax=42.0) == 42.0
        # empty histogram
        import math

        assert math.isnan(histogram_quantile(bounds, [0, 0, 0, 0], 0.5))

    def test_quantile_never_underestimates_exact_percentile(self):
        """For any sample set, the histogram quantile >= the exact
        nearest-rank percentile on the raw values (the conservative
        contract a tail-latency gate needs)."""
        import random

        rng = random.Random(7)
        values = [rng.uniform(1e-4, 5.0) for _ in range(200)]
        m = MetricsRegistry()
        h = m.histogram("lat")
        for v in values:
            h.observe(v)
        exact = sorted(values)
        for q in (0.5, 0.9, 0.99):
            assert h.quantile(q) >= percentile(exact, q) - 1e-12


# ---------------------------------------------------------------------------
# exactness under concurrency (the service-lock satellite)
# ---------------------------------------------------------------------------

class TestConcurrencyExactness:
    def test_concurrent_observers_exact_counts(self):
        m = MetricsRegistry()
        h = m.histogram("lat", bounds=exponential_bounds(1e-4, 2.0, 20))
        c = m.counter("n")
        n_threads, per_thread = 8, 500

        def work(tid):
            for i in range(per_thread):
                h.observe(1e-4 * (1 + (i * (tid + 1)) % 1000))
                c.inc()

        threads = [threading.Thread(target=work, args=(t,))
                   for t in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        snap = m.snapshot()
        want = n_threads * per_thread
        assert snap["counters"]["n"] == want
        hist = snap["histograms"]["lat"]
        assert hist["count"] == want, "dropped or double-counted observation"
        assert sum(hist["counts"]) == want, "bucket counts disagree with count"


# ---------------------------------------------------------------------------
# snapshot / merge / exporters
# ---------------------------------------------------------------------------

class TestSnapshotAndExport:
    def _registry(self):
        m = MetricsRegistry()
        m.counter("serve.submits").inc(5)
        m.gauge("serve.queued_tokens").set(128)
        h = m.histogram("serve.e2e_s", bounds=[0.1, 1.0])
        for v in (0.05, 0.5, 2.0):
            h.observe(v)
        return m

    def test_snapshot_shape_and_quantiles(self):
        snap = self._registry().snapshot()
        h = snap["histograms"]["serve.e2e_s"]
        assert h["count"] == 3 and h["counts"] == [1, 1, 1]
        assert h["p50"] == 1.0  # middle bucket's upper bound
        assert h["p99"] == 2.0  # overflow clamped to max
        assert snap["counters"]["serve.submits"] == 5.0

    def test_merge_adds_counters_and_buckets(self):
        a, b = self._registry().snapshot(), self._registry().snapshot()
        merged = merge_snapshots(a, b)
        assert merged["counters"]["serve.submits"] == 10.0
        h = merged["histograms"]["serve.e2e_s"]
        assert h["count"] == 6 and h["counts"] == [2, 2, 2]
        assert h["max"] == 2.0 and h["p99"] == 2.0

    def test_merge_refuses_mismatched_bounds(self):
        a = self._registry().snapshot()
        other = MetricsRegistry()
        other.histogram("serve.e2e_s", bounds=[0.5]).observe(0.1)
        with pytest.raises(ValueError, match="mismatched bucket"):
            merge_snapshots(a, other.snapshot())

    def test_json_line_is_one_line_finite(self):
        line = to_json_line(self._registry().snapshot())
        assert "\n" not in line
        doc = json.loads(line)  # NaN/inf would fail strict JSON
        assert doc["histograms"]["serve.e2e_s"]["count"] == 3

    def test_prometheus_exposition(self):
        text = to_prometheus(self._registry().snapshot())
        lines = text.splitlines()
        assert "# TYPE gigapath_serve_submits counter" in lines
        assert "gigapath_serve_submits 5" in lines
        assert "# TYPE gigapath_serve_e2e_s histogram" in lines
        # cumulative buckets, +Inf equals the total count
        assert 'gigapath_serve_e2e_s_bucket{le="0.1"} 1' in lines
        assert 'gigapath_serve_e2e_s_bucket{le="1"} 2' in lines
        assert 'gigapath_serve_e2e_s_bucket{le="+Inf"} 3' in lines
        assert "gigapath_serve_e2e_s_count 3" in lines

    def test_shared_percentile_is_the_obs_report_one(self):
        import obs_report

        assert obs_report.percentile is percentile


# ---------------------------------------------------------------------------
# env-gated construction + flushing
# ---------------------------------------------------------------------------

class TestGetMetrics:
    def test_null_runlog_yields_null_registry(self):
        m = get_metrics(NullRunLog())
        assert isinstance(m, NullMetricsRegistry)
        assert not isinstance(m, MetricsRegistry)
        # the null instruments absorb everything
        m.counter("x").inc()
        m.histogram("h").observe(1.0)
        assert m.snapshot()["counters"] == {}

    def test_metrics_flag_off_yields_null_registry(self, tmp_path,
                                                   monkeypatch):
        monkeypatch.setenv("GIGAPATH_METRICS", "0")
        log = RunLog(str(tmp_path / "run.jsonl"), driver="t", echo=False)
        try:
            assert not isinstance(get_metrics(log), MetricsRegistry)
        finally:
            log.close()

    def test_attach_once_and_final_flush_inside_run_end(self, tmp_path):
        path = str(tmp_path / "run.jsonl")
        log = RunLog(path, driver="t", echo=False)
        m = get_metrics(log)
        assert isinstance(m, MetricsRegistry)
        assert get_metrics(log) is m, "one registry per runlog"
        m.counter("steps").inc(3)
        log.run_end(status="ok")
        events = [json.loads(line) for line in open(path)]
        finals = [ev for ev in events if ev["kind"] == "metrics"]
        assert len(finals) == 1 and finals[0]["reason"] == "final"
        assert finals[0]["counters"]["steps"] == 3.0

    def test_periodic_flush_interval(self, tmp_path):
        path = str(tmp_path / "run.jsonl")
        log = RunLog(path, driver="t", echo=False)
        try:
            m = MetricsRegistry(runlog=log, interval_s=0.0)
            assert m.maybe_flush() is None  # interval 0 = periodic off
            m.interval_s = 1e-9
            m.counter("x").inc()
            assert m.maybe_flush() is not None
        finally:
            log.close()

    def test_textfile_written_atomically_on_flush(self, tmp_path):
        log = RunLog(str(tmp_path / "run.jsonl"), driver="t", echo=False)
        try:
            textfile = str(tmp_path / "prom" / "gigapath.prom")
            m = MetricsRegistry(runlog=log, textfile=textfile)
            m.counter("reqs").inc(2)
            m.flush(reason="final")
            text = open(textfile).read()
            assert "gigapath_reqs 2" in text
            assert not [p for p in os.listdir(os.path.dirname(textfile))
                        if ".tmp." in p], "tmp file left behind"
        finally:
            log.close()


# ---------------------------------------------------------------------------
# SLO burn tracking
# ---------------------------------------------------------------------------

def _tracker(log=None, **kw):
    base = dict(budget=0.25, short_window_s=10.0, long_window_s=20.0,
                burn_threshold=1.5, min_events=4, runlog=log, name="t")
    base.update(kw)
    return SloTracker(0.1, **base)


class TestSloTracker:
    def test_validation(self):
        with pytest.raises(ValueError):
            SloTracker(0.0)
        with pytest.raises(ValueError):
            SloTracker(1.0, budget=0.0)
        with pytest.raises(ValueError):
            SloTracker(1.0, short_window_s=10, long_window_s=5)

    def test_transition_edged_burn_and_recovery(self):
        slo = _tracker()
        # 4 fast requests: no burn
        for i in range(4):
            assert slo.observe(0.01, now=float(i)) is None
        assert not slo.burning
        # a slow regime: all-slow -> burn 1/0.25 = 4x >= 1.5 on both
        # windows. ONE transition record, not one per request
        records = [slo.observe(0.5, now=4.0 + 0.1 * i) for i in range(8)]
        fired = [r for r in records if r is not None]
        assert len(fired) == 1 and fired[0]["burning"] is True
        assert slo.burning and slo.burn_entries == 1
        # recovery: fast requests age the slow ones out of both windows
        rec = None
        for i in range(60):
            r = slo.observe(0.01, now=6.0 + 0.5 * i)
            rec = r if (r is not None and not r["burning"]) else rec
        assert rec is not None and slo.burning is False

    def test_min_events_floor_blocks_early_fire(self):
        slo = _tracker(min_events=16)
        for i in range(8):  # every one slow, but only 8 events
            assert slo.observe(9.9, now=float(i) * 0.1) is None
        assert not slo.burning

    def test_short_blip_does_not_burn_long_window(self):
        """One slow burst inside an otherwise healthy LONG window must
        not page: the long-window burn stays under threshold."""
        slo = _tracker(budget=0.05, short_window_s=1.0, long_window_s=20.0)
        t = 0.0
        for _ in range(96):  # 96 good events across the long window
            slo.observe(0.01, now=t)
            t += 0.2
        burned = [slo.observe(0.5, now=t + 0.01 * i) for i in range(3)]
        # short window is all-slow (burn 20x) but the long window holds
        assert all(r is None for r in burned) and not slo.burning

    def test_slo_events_land_on_runlog_and_final_status(self, tmp_path):
        path = str(tmp_path / "run.jsonl")
        log = RunLog(path, driver="t", echo=False)
        slo = _tracker(log=log)
        for i in range(6):
            slo.observe(0.5, now=float(i) * 0.1)
        slo.emit_status()
        log.close()
        events = [json.loads(line) for line in open(path)]
        slos = [ev for ev in events if ev["kind"] == "slo"]
        assert len(slos) == 2
        assert slos[0]["burning"] is True and "final" not in slos[0]
        assert slos[1]["final"] is True
        assert slos[1]["violations"] == 6 and slos[1]["total"] == 6

    def test_failures_burn_the_budget(self):
        """A failure storm with ZERO successful latencies must still
        burn: observe_failure records a spent unit of error budget (the
        deadline-expired / breaker-shed / dispatch-error path)."""
        slo = _tracker()
        records = [slo.observe_failure(now=float(i) * 0.1)
                   for i in range(6)]
        fired = [r for r in records if r is not None]
        assert len(fired) == 1 and fired[0]["burning"] is True
        assert fired[0]["latency_s"] is None  # no latency to report
        assert slo.violations == 6 and slo.total == 6

    def test_null_tracker_absorbs(self):
        slo = NullSloTracker()
        slo.observe(99.0)
        slo.observe_failure()
        slo.emit_status()
        assert slo.status() == {} and not slo.burning
