"""Serving stack (gigapath_tpu/serve): bucket ladder, continuous-batch
coalescer, content-hash cache, per-bucket AOT executables, and the full
queue -> bucket -> AOT -> cache service end to end on CPU (ISSUE 7
acceptance).

The pinned invariants:

- **padding parity**: a bucketed padded forward (key-padding mask) ==
  the exact-shape forward at 1e-5, across ragged tile counts including
  the bucket-boundary N and N=1;
- **compile count**: serving M slides of K distinct lengths over J
  buckets compiles exactly J executables — watchdog-counted AND
  XLA-layer-counted — and a warm restart from persisted artifacts
  compiles ZERO (the cold-start acceptance of ROADMAP item 1);
- **cache short-circuit**: repeated slides resolve with no forward pass
  (dispatch-count pinned).
"""

import glob
import io
import json
import logging
import os
import sys
import threading
import time

import jax
import numpy as np
import pytest

from gigapath_tpu.serve import (
    BucketLadder,
    EmbeddingCache,
    RequestQueue,
    ServeConfig,
    SlideRequest,
    SlideService,
    assemble_batch,
    content_key,
    pad_slide,
)

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                    "scripts"),
)


# ---------------------------------------------------------------------------
# bucket ladder
# ---------------------------------------------------------------------------

class TestBucketLadder:
    def test_geometric_rungs_aligned_and_increasing(self):
        ladder = BucketLadder(n_min=1024, growth=2.0, n_max=1 << 20)
        rungs = ladder.rungs
        assert rungs[0] == 1024 and rungs[-1] >= 1 << 20
        assert all(r % 128 == 0 for r in rungs)
        assert all(b > a for a, b in zip(rungs, rungs[1:]))
        # geometric: a small fixed set, not one per length
        assert len(rungs) <= 12

    def test_bucket_for_boundaries(self):
        ladder = BucketLadder(n_min=16, growth=2.0, n_max=64, align=16)
        assert ladder.rungs == (16, 32, 64)
        assert ladder.bucket_for(1) == 16
        assert ladder.bucket_for(16) == 16      # exact fit pays no padding
        assert ladder.bucket_for(17) == 32
        assert ladder.bucket_for(64) == 64
        with pytest.raises(ValueError):
            ladder.bucket_for(65)
        with pytest.raises(ValueError):
            ladder.bucket_for(0)

    def test_constructor_validation(self):
        with pytest.raises(ValueError):
            BucketLadder(n_min=0)
        with pytest.raises(ValueError):
            BucketLadder(growth=1.0)
        with pytest.raises(ValueError):
            BucketLadder(n_min=100, n_max=50)

    def test_pad_slide_and_mask(self, rng):
        feats = rng.normal(size=(5, 8)).astype(np.float32)
        coords = rng.uniform(0, 100, (5, 2)).astype(np.float32)
        f, c, m = pad_slide(feats, coords, 16)
        assert f.shape == (16, 8) and c.shape == (16, 2) and m.shape == (16,)
        np.testing.assert_array_equal(f[:5], feats)
        assert not f[5:].any() and not c[5:].any()
        assert m[:5].all() and not m[5:].any()
        # no coords -> zeros, mask unchanged
        f2, c2, m2 = pad_slide(feats, None, 16)
        assert not c2.any() and m2.sum() == 5
        with pytest.raises(ValueError):
            pad_slide(feats, coords, 4)  # does not fit

    def test_assemble_batch_pads_batch_dim_with_masked_rows(self, rng):
        slides = [
            (rng.normal(size=(n, 8)).astype(np.float32), None)
            for n in (3, 7)
        ]
        embeds, coords, mask = assemble_batch(slides, 16, capacity=4)
        assert embeds.shape == (4, 16, 8)
        assert mask[0].sum() == 3 and mask[1].sum() == 7
        assert not mask[2:].any() and not embeds[2:].any()
        with pytest.raises(ValueError):
            assemble_batch(slides, 16, capacity=1)
        with pytest.raises(ValueError):
            assemble_batch([], 16, capacity=2)  # needs feature_dim
        e, c, m = assemble_batch([], 16, capacity=2, feature_dim=8)
        assert e.shape == (2, 16, 8) and not m.any()


# ---------------------------------------------------------------------------
# request queue (continuous batching policy; deterministic clock)
# ---------------------------------------------------------------------------

def _req(n_tiles, bucket_n, t, sid="s"):
    return SlideRequest(sid, np.zeros((n_tiles, 4), np.float32), None,
                        bucket_n=bucket_n, t_submit=t)


class TestRequestQueue:
    def test_full_bucket_dispatches_immediately(self):
        q = RequestQueue(max_batch=2, max_wait_s=10.0)
        q.submit(_req(3, 16, t=0.0, sid="a"))
        assert q.pop_ready(now=0.001) == []  # not full, deadline far
        q.submit(_req(4, 16, t=0.002, sid="b"))
        batch = q.pop_ready(now=0.003)
        assert [r.slide_id for r in batch] == ["a", "b"]  # FIFO
        assert q.pending() == 0

    def test_deadline_dispatches_partial_batch(self):
        q = RequestQueue(max_batch=4, max_wait_s=0.05)
        q.submit(_req(3, 16, t=0.0))
        assert q.pop_ready(now=0.02) == []          # young: keep waiting
        assert q.next_deadline_s(now=0.02) == pytest.approx(0.03)
        batch = q.pop_ready(now=0.06)               # deadline passed
        assert len(batch) == 1

    def test_batches_never_mix_buckets(self):
        q = RequestQueue(max_batch=2, max_wait_s=0.0)
        q.submit(_req(3, 16, t=0.0, sid="a16"))
        q.submit(_req(20, 32, t=0.001, sid="a32"))
        q.submit(_req(4, 16, t=0.002, sid="b16"))
        first = q.pop_ready(now=0.01)
        assert {r.bucket_n for r in first} == {16}
        assert [r.slide_id for r in first] == ["a16", "b16"]
        second = q.pop_ready(now=0.01)
        assert [r.slide_id for r in second] == ["a32"]

    def test_full_bucket_beats_deadline_and_caps_at_max_batch(self):
        q = RequestQueue(max_batch=2, max_wait_s=0.01)
        q.submit(_req(20, 32, t=0.0, sid="old32"))      # oldest, not full
        q.submit(_req(3, 16, t=0.005, sid="a16"))
        q.submit(_req(4, 16, t=0.006, sid="b16"))
        q.submit(_req(5, 16, t=0.007, sid="c16"))
        batch = q.pop_ready(now=0.006)  # 32-lane deadline NOT passed
        assert [r.slide_id for r in batch] == ["a16", "b16"]  # full wins, capped
        assert q.pending() == 2

    def test_expired_deadline_beats_full_bucket(self):
        # starvation guard: sustained hot-bucket traffic (the 16-lane
        # refills to full between polls) must not defer an EXPIRED
        # odd-sized head forever — max_wait_s is a bound, not a hint
        q = RequestQueue(max_batch=2, max_wait_s=0.01)
        q.submit(_req(20, 32, t=0.0, sid="old32"))
        q.submit(_req(3, 16, t=0.005, sid="a16"))
        q.submit(_req(4, 16, t=0.006, sid="b16"))
        batch = q.pop_ready(now=0.02)  # 32-lane deadline passed
        assert [r.slide_id for r in batch] == ["old32"]
        # the displaced full lane dispatches on the very next poll
        assert [r.slide_id for r in q.pop_ready(now=0.02)] == ["a16", "b16"]
        assert q.pending() == 0

    def test_drain_flushes_leftovers(self):
        q = RequestQueue(max_batch=4, max_wait_s=100.0)
        q.submit(_req(3, 16, t=0.0))
        assert q.pop_ready(now=0.01) == []
        assert len(q.pop_ready(now=0.01, drain=True)) == 1
        assert q.pop_ready(now=0.01, drain=True) == []
        assert q.next_deadline_s() is None

    def test_wait_for_work_wakes_on_submit(self):
        q = RequestQueue(max_batch=2, max_wait_s=1.0)
        woke = threading.Event()

        def waiter():
            q.wait_for_work(timeout=5.0)
            woke.set()

        t = threading.Thread(target=waiter)
        t.start()
        q.submit(_req(3, 16, t=0.0))
        t.join(timeout=5.0)
        assert woke.is_set()

    def test_per_bucket_capacity_caps_big_buckets(self):
        # token-budget clamp: a big bucket fills (and dispatches) at a
        # smaller batch than max_batch so one dispatch never pads more
        # tiles than the budget
        q = RequestQueue(max_batch=4, max_wait_s=100.0,
                         capacity_for=lambda n: 64 // n)
        assert q.capacity(16) == 4   # min(4, 64//16=4)
        assert q.capacity(32) == 2
        assert q.capacity(128) == 1  # floor: never below 1
        q.submit(_req(30, 32, t=0.0, sid="a"))
        assert q.pop_ready(now=0.001) == []   # capacity 2: not full yet
        q.submit(_req(31, 32, t=0.002, sid="b"))
        q.submit(_req(29, 32, t=0.003, sid="c"))
        batch = q.pop_ready(now=0.004)        # full at 2, capped at 2
        assert [r.slide_id for r in batch] == ["a", "b"]
        assert q.pending() == 1

    def test_wait_for_work_parks_on_pending_but_undispatchable(self):
        # a pending request whose deadline is still far away must PARK
        # the worker (early-returning would busy-spin it for the whole
        # max_wait_s window); a full lane or an expired deadline must
        # return immediately
        q = RequestQueue(max_batch=2, max_wait_s=10.0)
        q.submit(_req(3, 16, t=0.0, sid="young"))
        t0 = time.monotonic()
        q.wait_for_work(timeout=0.2, now=0.001)  # young + not full: park
        assert time.monotonic() - t0 >= 0.15
        q.wait_for_work(timeout=5.0, now=11.0)   # deadline expired: immediate
        assert time.monotonic() - t0 < 2.0
        q.submit(_req(4, 16, t=0.002, sid="fills"))
        t1 = time.monotonic()
        q.wait_for_work(timeout=5.0, now=0.003)  # lane full: immediate
        assert time.monotonic() - t1 < 2.0


# ---------------------------------------------------------------------------
# content-hash cache
# ---------------------------------------------------------------------------

class TestEmbeddingCache:
    def test_content_key_is_content_not_identity(self, rng):
        feats = rng.normal(size=(5, 4)).astype(np.float32)
        coords = rng.uniform(0, 10, (5, 2)).astype(np.float32)
        assert content_key(feats, coords) == content_key(
            feats.copy(), coords.copy()
        )
        bumped = feats.copy()
        bumped[0, 0] += 1e-3
        assert content_key(feats, coords) != content_key(bumped, coords)
        assert content_key(feats, coords) != content_key(feats, None)
        assert content_key(feats, coords) != content_key(
            feats, coords, extra="other-model"
        )

    def test_lru_eviction_respects_byte_budget_and_recency(self):
        a = np.zeros(10, np.float64)  # 80 bytes each
        cache = EmbeddingCache(budget_bytes=200)
        cache.put("k1", a)
        cache.put("k2", a.copy())
        assert cache.get("k1") is not None  # refresh k1 -> k2 is LRU
        cache.put("k3", a.copy())           # evicts k2
        assert cache.get("k2") is None
        assert cache.get("k1") is not None and cache.get("k3") is not None
        assert cache.evictions == 1 and cache.bytes <= 200

    def test_oversized_value_served_but_never_cached(self):
        cache = EmbeddingCache(budget_bytes=64)
        assert not cache.put("big", np.zeros(100, np.float64))
        assert len(cache) == 0

    def test_stats_hit_rate(self):
        cache = EmbeddingCache()
        cache.put("k", np.zeros(2))
        cache.get("k")
        cache.get("missing")
        s = cache.stats()
        assert s["hits"] == 1 and s["misses"] == 1
        assert s["hit_rate"] == pytest.approx(0.5)


# ---------------------------------------------------------------------------
# padding parity (satellite): bucketed+masked forward == exact forward
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def tiny_model(serve_tiny_model):
    # f32 (dtype=None), unlike inference.load_model's bf16 default: the
    # 1e-5 parity bar is a float32 statement (bf16 resolution is ~2^-8).
    # Built ONCE per session in conftest.py (shared with test_serve_obs)
    return serve_tiny_model


def _forward_fn(model):
    def forward(p, embeds, coords, pad_mask):
        return model.apply({"params": p}, embeds, coords,
                           pad_mask=pad_mask, deterministic=True)

    return forward


class TestPaddingParity:
    @pytest.mark.parametrize("n_tiles", [1, 5, 16, 17, 31, 32])
    def test_bucketed_logits_match_exact(self, tiny_model, rng, n_tiles):
        """Ragged tile counts, including the bucket-boundary fits (16,
        32 land exactly ON a rung of this ladder) and the N=1 edge."""
        model, params = tiny_model
        ladder = BucketLadder(n_min=16, growth=2.0, n_max=64, align=16)
        feats = rng.normal(size=(n_tiles, 16)).astype(np.float32)
        coords = rng.uniform(0, 25000, (n_tiles, 2)).astype(np.float32)

        exact = np.asarray(model.apply(
            {"params": params}, feats[None], coords[None],
            deterministic=True,
        ), np.float32)

        bucket_n = ladder.bucket_for(n_tiles)
        embeds, c, mask = assemble_batch([(feats, coords)], bucket_n,
                                         capacity=3)
        out = np.asarray(_forward_fn(model)(params, embeds, c, mask),
                         np.float32)
        np.testing.assert_allclose(out[0], exact[0], atol=1e-5)
        # dummy batch rows stay finite (cls attends to itself) so they
        # can never poison a dispatch
        assert np.isfinite(out).all()

    def test_batch_position_does_not_change_logits(self, tiny_model, rng):
        """A slide's logits are independent of its batch row and of its
        batch company — the property that makes coalescing safe."""
        model, params = tiny_model
        forward = _forward_fn(model)
        a = rng.normal(size=(7, 16)).astype(np.float32)
        ca = rng.uniform(0, 25000, (7, 2)).astype(np.float32)
        b = rng.normal(size=(12, 16)).astype(np.float32)
        cb = rng.uniform(0, 25000, (12, 2)).astype(np.float32)

        alone = np.asarray(forward(
            params, *assemble_batch([(a, ca)], 16, capacity=2)
        ), np.float32)[0]
        together = np.asarray(forward(
            params, *assemble_batch([(b, cb), (a, ca)], 16, capacity=2)
        ), np.float32)
        np.testing.assert_allclose(together[1], alone, atol=1e-5)


# ---------------------------------------------------------------------------
# the service end to end (acceptance: queue -> bucket -> AOT -> cache)
# ---------------------------------------------------------------------------

class _XlaCompileCounter(logging.Handler):
    """XLA-layer compile truth via jax_log_compiles, independent of the
    watchdog's own accounting (same pattern as tests/test_anomaly.py)."""

    def __init__(self):
        super().__init__()
        self.count = 0

    def emit(self, record):
        if "Finished XLA compilation of" in record.getMessage():
            self.count += 1


class _count_xla_compiles:
    def __enter__(self):
        self.counter = _XlaCompileCounter()
        self.logger = logging.getLogger("jax._src.dispatch")
        self.prev_level = self.logger.level
        self.logger.addHandler(self.counter)
        self.logger.setLevel(logging.DEBUG)
        jax.config.update("jax_log_compiles", True)
        return self.counter

    def __exit__(self, *exc):
        jax.config.update("jax_log_compiles", False)
        self.logger.setLevel(self.prev_level)
        self.logger.removeHandler(self.counter)


def _tiny_config(tmp_path, **overrides):
    base = dict(
        max_batch=3, max_wait_s=0.01, bucket_min=16, bucket_growth=2.0,
        bucket_max=64, bucket_align=16, feature_dim=16,
        artifact_dir=str(tmp_path / "artifacts"),
    )
    base.update(overrides)
    return ServeConfig(**base)


def _make_slides(rng, lengths):
    return [
        (
            f"s{i}_n{n}",
            rng.normal(size=(n, 16)).astype(np.float32),
            rng.uniform(0, 25000, (n, 2)).astype(np.float32),
        )
        for i, n in enumerate(lengths)
    ]


class TestSlideServiceEndToEnd:
    def test_queue_bucket_aot_cache_path(self, tiny_model, rng, tmp_path,
                                         monkeypatch):
        """The tier-1 acceptance: M=10 slides of K=5 distinct lengths
        over J=3 buckets -> exactly J executables (watchdog AND
        XLA-layer counted), repeats served from the cache without a
        dispatch, warm restart compiles zero."""
        monkeypatch.delenv("GIGAPATH_OBS", raising=False)
        model, params = tiny_model
        forward = _forward_fn(model)
        config = _tiny_config(tmp_path)
        # 5 distinct lengths -> buckets {16, 32, 64}
        lengths = [1, 7, 16, 20, 33, 1, 7, 16, 20, 33]
        slides = _make_slides(rng, lengths[:5]) + _make_slides(
            np.random.default_rng(7), lengths[5:]
        )
        assert len({f.shape[0] for _, f, _ in slides}) == 5

        service = SlideService(forward, params, config=config,
                               out_dir=str(tmp_path), identity="tiny")
        with _count_xla_compiles() as xla:
            futs = [service.submit(sid, f, c) for sid, f, c in slides]
            service.drain()
            results = [fut.result(timeout=60) for fut in futs]
        assert all(r.shape == (2,) for r in results)

        # -- compile-count pin: exactly J executables, both layers ------
        assert service.aot.compiled_count == 3
        assert sum(service.watchdog.compile_count.values()) == 3
        assert service.watchdog.unexpected_retraces == []
        assert xla.count == 3
        assert service.stats()["buckets_used"] == 3

        # -- parity: every slide matches its exact-shape forward --------
        for (sid, f, c), res in zip(slides, results):
            exact = np.asarray(model.apply(
                {"params": params}, f[None], c[None], deterministic=True,
            ), np.float32)[0]
            np.testing.assert_allclose(res, exact, atol=1e-5)

        # -- cache short-circuit: repeats cause ZERO dispatches ---------
        dispatches = service.dispatch_count
        with _count_xla_compiles() as xla2:
            repeat_futs = [
                service.submit(f"again_{sid}", f, c) for sid, f, c in slides
            ]
            repeats = [fut.result(timeout=5) for fut in repeat_futs]
        assert service.dispatch_count == dispatches
        assert xla2.count == 0
        for orig, again in zip(results, repeats):
            np.testing.assert_array_equal(orig, again)
        assert service.cache.stats()["hits"] == len(slides)

        # -- results are COPIES of their row, never views of the padded
        # batch buffer (a view would pin capacity x bucket_n x D bytes
        # per cache line against a budget that accounts one row), and
        # read-only (the same array backs the future AND the cache line
        # — silent mutation would corrupt later hits)
        for res in results:
            assert res.base is None or res.base.shape == res.shape
            assert not res.flags.writeable
            with pytest.raises(ValueError):
                res[0] = 0.0
        service.close()

        # -- obs artifact: serving telemetry + report section -----------
        run_files = [
            p for p in glob.glob(str(tmp_path / "obs" / "serve-*.jsonl"))
            if "flight-" not in os.path.basename(p)
        ]
        assert len(run_files) == 1
        events = [json.loads(line) for line in open(run_files[0])]
        kinds = {ev["kind"] for ev in events}
        assert {"run_start", "serve_dispatch", "cache_hit", "compile",
                "compile_profile", "step", "span", "run_end"} <= kinds
        serve_events = [ev for ev in events if ev["kind"] == "serve_dispatch"]
        assert sum(ev["slides"] for ev in serve_events) == len(slides)
        assert all(ev["capacity"] == 3 for ev in serve_events)
        # the ledger adopted each executable with a FULL profile and no
        # extra XLA compile (xla.count above pinned that already)
        profiles = [ev for ev in events if ev["kind"] == "compile_profile"]
        assert len(profiles) == 3
        assert all(ev.get("cost") is not None for ev in profiles)

        import obs_report

        buf = io.StringIO()
        assert obs_report.render(events, out=buf) == 0
        text = buf.getvalue()
        assert "== serving ==" in text
        assert "per-bucket dispatch table" in text
        assert "hit rate" in text

        # -- warm restart: artifacts load, nothing compiles -------------
        warm = SlideService(forward, params, config=config,
                            out_dir=str(tmp_path), identity="tiny")
        with _count_xla_compiles() as xla3:
            futs = [warm.submit(sid, f, c) for sid, f, c in slides[:5]]
            warm.drain()
            warm_results = [fut.result(timeout=60) for fut in futs]
        assert xla3.count == 0
        assert warm.aot.compiled_count == 0
        assert warm.aot.loaded_count == 3
        for orig, again in zip(results[:5], warm_results):
            np.testing.assert_allclose(orig, again, atol=1e-6)
        warm.close()

        # -- stale-code guard: a restart whose FORWARD changed (same
        # arch name, same param shapes) must RECOMPILE, not serve the
        # old artifact's semantics
        def changed_forward(p, embeds, coords, pad_mask):
            return forward(p, embeds, coords, pad_mask) * 2.0

        stale = SlideService(changed_forward, params, config=config,
                             out_dir=str(tmp_path), identity="tiny")
        fut = stale.submit(*slides[0])
        stale.drain()
        np.testing.assert_allclose(
            fut.result(timeout=60), 2.0 * results[0], atol=1e-5
        )
        assert stale.aot.loaded_count == 0  # fingerprint mismatch
        assert stale.aot.compiled_count == 1
        stale.close()

    def test_concurrent_submitters_through_worker_thread(
        self, tiny_model, rng, tmp_path
    ):
        """Async shape: the dispatch worker coalesces submissions from
        concurrent threads; every future resolves, nothing retraces."""
        from concurrent.futures import ThreadPoolExecutor

        model, params = tiny_model
        config = _tiny_config(tmp_path, max_batch=2, bucket_max=32)
        slides = _make_slides(rng, [1, 5, 9, 17, 20, 30, 12, 3])
        with SlideService(_forward_fn(model), params, config=config,
                          out_dir=str(tmp_path), identity="tiny") as service:
            with ThreadPoolExecutor(max_workers=4) as pool:
                futs = list(pool.map(lambda s: service.submit(*s), slides))
            results = [f.result(timeout=60) for f in futs]
            assert all(np.isfinite(r).all() for r in results)
            assert service.watchdog.unexpected_retraces == []
            assert service.aot.compiled_count == 2  # buckets {16, 32}
            assert service.slides_served == len(slides)
        for (sid, f, c), res in zip(slides[:2], results[:2]):
            exact = np.asarray(model.apply(
                {"params": params}, f[None], c[None], deterministic=True,
            ), np.float32)[0]
            np.testing.assert_allclose(res, exact, atol=1e-5)

    def test_inflight_duplicates_share_one_dispatch(self, tiny_model, rng,
                                                    tmp_path):
        model, params = tiny_model
        config = _tiny_config(tmp_path, artifact_dir=None)
        service = SlideService(_forward_fn(model), params, config=config,
                               out_dir=str(tmp_path), identity="tiny")
        feats = rng.normal(size=(5, 16)).astype(np.float32)
        coords = rng.uniform(0, 25000, (5, 2)).astype(np.float32)
        f1 = service.submit("a", feats, coords)
        f2 = service.submit("b", feats, coords)  # identical content
        assert f2 is f1  # joined the pending request
        assert service.inflight_joins == 1
        # a join is not a cache MISS: it never probes the result cache,
        # so duplicate-heavy traffic can't deflate the hit-rate metric
        assert service.cache.stats()["misses"] == 1
        service.drain()
        assert service.dispatch_count == 1
        assert f1.result(timeout=60) is f2.result(timeout=60)
        service.close()

    def test_batch_tokens_caps_big_bucket_capacity(self, tiny_model, rng,
                                                   tmp_path):
        """The token budget shrinks the batch axis for big buckets: with
        batch_tokens=64, bucket 16 batches 3 (max_batch) but bucket 64
        batches 1 — the compiled shapes (AOT keys) prove it."""
        model, params = tiny_model
        config = _tiny_config(tmp_path, artifact_dir=None, batch_tokens=64)
        service = SlideService(_forward_fn(model), params, config=config,
                               out_dir=str(tmp_path), identity="tiny")
        assert service.capacity_for(16) == 3   # min(max_batch=3, 64//16)
        assert service.capacity_for(64) == 1
        futs = [
            service.submit(f"s{i}", rng.normal(size=(n, 16)).astype(np.float32))
            for i, n in enumerate([5, 6, 7, 40])
        ]
        service.drain()
        for f in futs:
            f.result(timeout=60)
        assert set(service.aot.sources) == {(3, 16), (1, 64)}
        service.close()

    def test_submit_validation_and_close_semantics(self, tiny_model, rng,
                                                   tmp_path):
        model, params = tiny_model
        config = _tiny_config(tmp_path, artifact_dir=None)
        service = SlideService(_forward_fn(model), params, config=config,
                               out_dir=str(tmp_path), identity="tiny")
        with pytest.raises(ValueError):  # wrong feature dim
            service.submit("bad", rng.normal(size=(5, 8)).astype(np.float32))
        with pytest.raises(ValueError):  # exceeds the ladder's top rung
            service.submit("huge", rng.normal(size=(65, 16)).astype(np.float32))
        service.close()
        with pytest.raises(RuntimeError):
            service.submit("late", rng.normal(size=(5, 16)).astype(np.float32))

    def test_obs_off_service_leaves_no_artifacts(self, tiny_model, rng,
                                                 tmp_path, monkeypatch):
        """GIGAPATH_OBS=0: the service still serves (NullRunLog,
        NullLedger) and writes no obs files."""
        monkeypatch.setenv("GIGAPATH_OBS", "0")
        model, params = tiny_model
        config = _tiny_config(tmp_path, artifact_dir=None, bucket_max=16)
        service = SlideService(_forward_fn(model), params, config=config,
                               out_dir=str(tmp_path), identity="tiny")
        fut = service.submit(
            "s", rng.normal(size=(5, 16)).astype(np.float32),
            rng.uniform(0, 25000, (5, 2)).astype(np.float32),
        )
        service.drain()
        assert np.isfinite(fut.result(timeout=60)).all()
        service.close()
        assert not os.path.exists(tmp_path / "obs")


# ---------------------------------------------------------------------------
# the smoke script's own contract (small sizes; defaults run in the
# slow tier — scripts/serve_smoke.py itself is the ISSUE acceptance run)
# ---------------------------------------------------------------------------

class TestServeSmokeScript:
    def test_pick_lengths_terminates_on_tight_ladders(self):
        import serve_smoke

        from gigapath_tpu.serve import BucketLadder

        ladder = BucketLadder(n_min=16, growth=2.0, n_max=16, align=16)
        picked = serve_smoke.pick_lengths(ladder, 16)  # every length 1..16
        assert sorted(picked) == list(range(1, 17))
        with pytest.raises(ValueError):  # impossible ask: error, not a hang
            serve_smoke.pick_lengths(ladder, 20)

    def _run(self, tmp_path, extra):
        import serve_smoke

        json_path = str(tmp_path / "SERVE_SMOKE.json")
        rc = serve_smoke.main([
            "--out-dir", str(tmp_path / "out"), "--json", json_path,
        ] + extra)
        with open(json_path) as fh:
            return rc, json.load(fh)

    def test_small_smoke_end_to_end(self, tmp_path):
        rc, payload = self._run(tmp_path, [
            "--slides", "8", "--distinct-lengths", "4", "--repeats", "4",
            "--threads", "4", "--max-batch", "2", "--bucket-max", "64",
        ])
        assert rc == 0, payload
        assert payload["rc"] == 0
        assert payload["unexpected_retraces"] == 0
        assert payload["compiled_executables"] == payload["buckets_used"]
        assert payload["warm_compiled_executables"] == 0
        assert payload["warm_loaded_executables"] == payload["buckets_used"]
        assert payload["cache_hits"] >= 4
        assert payload["distinct_lengths"] == 4
        for key in ("slides_per_sec", "occupancy_mean", "queue_wait_p50_s",
                    "queue_wait_p90_s", "cache_hit_rate", "backend"):
            assert key in payload

    @pytest.mark.slow
    def test_default_scale_smoke(self, tmp_path):
        """The literal acceptance run: >= 32 concurrent slides of >= 6
        distinct lengths, zero mid-serve retraces, cache-pinned repeats,
        warm restart from artifacts."""
        rc, payload = self._run(tmp_path, [])
        assert rc == 0, payload
        assert payload["slides"] >= 32
        assert payload["distinct_lengths"] >= 6
        assert payload["unexpected_retraces"] == 0
        assert payload["warm_compiled_executables"] == 0


# ---------------------------------------------------------------------------
# ServeConfig env surface
# ---------------------------------------------------------------------------

class TestServeConfig:
    def test_from_env_reads_flags_once_with_override_priority(
        self, monkeypatch
    ):
        monkeypatch.setenv("GIGAPATH_SERVE_MAX_BATCH", "5")
        monkeypatch.setenv("GIGAPATH_SERVE_MAX_WAIT_S", "0.25")
        monkeypatch.setenv("GIGAPATH_SERVE_BATCH_TOKENS", "4096")
        monkeypatch.setenv("GIGAPATH_SERVE_CACHE_MB", "64")
        monkeypatch.setenv("GIGAPATH_SERVE_ARTIFACT_DIR", "/tmp/aots")
        monkeypatch.setenv("GIGAPATH_SERVE_BUCKET_MIN", "32")
        monkeypatch.setenv("GIGAPATH_SERVE_BUCKET_ALIGN", "32")
        cfg = ServeConfig.from_env()
        assert cfg.max_batch == 5
        assert cfg.max_wait_s == 0.25
        assert cfg.batch_tokens == 4096
        assert cfg.cache_budget_mb == 64
        assert cfg.artifact_dir == "/tmp/aots"
        assert cfg.bucket_min == 32 and cfg.bucket_align == 32
        # explicit overrides win over env
        assert ServeConfig.from_env(max_batch=2).max_batch == 2

    def test_defaults_without_env(self, monkeypatch):
        for flag in ("GIGAPATH_SERVE_MAX_BATCH", "GIGAPATH_SERVE_MAX_WAIT_S",
                     "GIGAPATH_SERVE_CACHE_MB",
                     "GIGAPATH_SERVE_ARTIFACT_DIR"):
            monkeypatch.delenv(flag, raising=False)
        cfg = ServeConfig.from_env()
        assert cfg.max_batch == 8 and cfg.artifact_dir is None
        assert cfg.bucket_min == 1024 and cfg.bucket_align == 128
