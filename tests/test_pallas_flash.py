"""Pallas flash kernel vs the jnp reference, in interpreter mode on CPU."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gigapath_tpu.ops.attention import attention_with_lse
from gigapath_tpu.ops.pallas_flash import pallas_flash_attention

flash = functools.partial(pallas_flash_attention, interpret=True)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("shape", [(1, 128, 2, 16), (2, 300, 3, 48)])
def test_forward_matches_reference(rng, causal, shape):
    q, k, v = (jnp.asarray(rng.normal(size=shape), jnp.float32) for _ in range(3))
    out, lse = flash(q, k, v, is_causal=causal)
    ref_out, ref_lse = attention_with_lse(q, k, v, is_causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref_out), atol=2e-5, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(lse), np.asarray(ref_lse), atol=2e-5, rtol=1e-4)


def test_forward_bf16(rng):
    shape = (1, 256, 2, 32)
    q, k, v = (jnp.asarray(rng.normal(size=shape), jnp.bfloat16) for _ in range(3))
    out, lse = flash(q, k, v)
    ref_out, ref_lse = attention_with_lse(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref_out, np.float32), atol=3e-2
    )
    np.testing.assert_allclose(np.asarray(lse), np.asarray(ref_lse), atol=3e-2, rtol=1e-2)


@pytest.mark.parametrize("causal", [False, True])
def test_gradients_match_reference(rng, causal):
    shape = (1, 192, 2, 16)
    q, k, v = (jnp.asarray(rng.normal(size=shape), jnp.float32) for _ in range(3))

    def loss_flash(q, k, v):
        out, _ = flash(q, k, v, is_causal=causal)
        return (out * out).sum()

    def loss_ref(q, k, v):
        out, _ = attention_with_lse(q, k, v, is_causal=causal)
        return (out * out).sum()

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g1, g2, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-4, rtol=1e-3,
            err_msg=f"d{name} mismatch",
        )


@pytest.mark.parametrize("lens", [[7, 64, 0, 33], [64, 64, 64, 64], [1, 2, 3, 4]])
def test_kv_len_ragged_masking(rng, lens):
    """Per-(batch,head) valid-key counts: forward, lse, and grads must match
    the jnp reference with the same kv_valid_len (incl. a zero-length row)."""
    B, L, H, D = 2, 64, 2, 16
    kv = np.asarray(lens, np.int32).reshape(B, H)
    q, k, v = (jnp.asarray(rng.normal(size=(B, L, H, D)), jnp.float32) for _ in range(3))
    out_p, lse_p = flash(q, k, v, kv_len=kv)
    out_j, lse_j = attention_with_lse(q, k, v, kv_valid_len=kv)
    np.testing.assert_allclose(np.asarray(out_p), np.asarray(out_j), atol=2e-5, rtol=1e-4)
    # lse is implementation-defined (~NEG_INF scale) on zero-valid rows;
    # both paths give such rows ~zero weight in the dilated branch fusion
    nonempty = (kv > 0)[:, :, None] * np.ones((B, H, L), bool)
    np.testing.assert_allclose(
        np.asarray(lse_p)[nonempty], np.asarray(lse_j)[nonempty], atol=2e-4, rtol=1e-4
    )

    def loss_p(q, k, v):
        o, _ = flash(q, k, v, kv_len=kv)
        return (o * o).sum()

    def loss_j(q, k, v):
        o, _ = attention_with_lse(q, k, v, kv_valid_len=kv)
        return (o * o).sum()

    g1 = jax.grad(loss_p, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_j, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g1, g2, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-4, rtol=1e-3, err_msg=f"d{name}"
        )


def test_kv_len_masks_large_real_keys(rng):
    """Masked key slots holding LARGE real activations (alignment padding
    becomes nonzero after residual layers) must not perturb outputs, lse, or
    gradients — a post-softmax zero-multiply would let them dominate the
    running max (underflowing valid rows) and produce inf*0 NaNs in the
    backward. Regression for the column-bias masking."""
    B, L, H, D = 1, 64, 2, 16
    n_valid = 40
    kv = np.full((B, H), n_valid, np.int32)
    q = jnp.asarray(rng.normal(size=(B, L, H, D)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, L, H, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, L, H, D)), jnp.float32)
    # masked tail keys are huge -> logits ~ +-40*|q| >> valid logits
    k = k.at[:, n_valid:].set(40.0)
    v = v.at[:, n_valid:].set(40.0)

    out_p, lse_p = flash(q, k, v, kv_len=kv)
    ref, lse_ref = attention_with_lse(
        q, k[:, :n_valid], v[:, :n_valid]
    )
    np.testing.assert_allclose(np.asarray(out_p), np.asarray(ref), atol=2e-5, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(lse_p), np.asarray(lse_ref), atol=2e-4, rtol=1e-4)

    def loss_p(q, k, v):
        o, _ = flash(q, k, v, kv_len=kv)
        return (o * o).sum()

    grads = jax.grad(loss_p, argnums=(0, 1, 2))(q, k, v)
    for g, name in zip(grads, "qkv"):
        assert np.isfinite(np.asarray(g)).all(), f"d{name} has NaN/inf"
    # masked key/value slots receive zero gradient
    np.testing.assert_allclose(np.asarray(grads[1][:, n_valid:]), 0.0, atol=1e-6)
    np.testing.assert_allclose(np.asarray(grads[2][:, n_valid:]), 0.0, atol=1e-6)


def test_unaligned_lengths(rng):
    """L not a multiple of the block size: padded keys must be masked."""
    q, k, v = (jnp.asarray(rng.normal(size=(1, 333, 2, 48)), jnp.float32) for _ in range(3))
    out, lse = flash(q, k, v)
    ref_out, ref_lse = attention_with_lse(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref_out), atol=2e-5, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(lse), np.asarray(ref_lse), atol=2e-5, rtol=1e-4)
