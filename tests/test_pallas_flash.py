"""Pallas flash kernel vs the jnp reference, in interpreter mode on CPU."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gigapath_tpu.ops.attention import attention_with_lse
from gigapath_tpu.ops.pallas_flash import pallas_flash_attention

flash = functools.partial(pallas_flash_attention, interpret=True)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("shape", [(1, 128, 2, 16), (2, 300, 3, 48)])
def test_forward_matches_reference(rng, causal, shape):
    q, k, v = (jnp.asarray(rng.normal(size=shape), jnp.float32) for _ in range(3))
    out, lse = flash(q, k, v, is_causal=causal)
    ref_out, ref_lse = attention_with_lse(q, k, v, is_causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref_out), atol=2e-5, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(lse), np.asarray(ref_lse), atol=2e-5, rtol=1e-4)


def test_forward_bf16(rng):
    shape = (1, 256, 2, 32)
    q, k, v = (jnp.asarray(rng.normal(size=shape), jnp.bfloat16) for _ in range(3))
    out, lse = flash(q, k, v)
    ref_out, ref_lse = attention_with_lse(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref_out, np.float32), atol=3e-2
    )
    np.testing.assert_allclose(np.asarray(lse), np.asarray(ref_lse), atol=3e-2, rtol=1e-2)


@pytest.mark.parametrize("causal", [False, True])
def test_gradients_match_reference(rng, causal):
    shape = (1, 192, 2, 16)
    q, k, v = (jnp.asarray(rng.normal(size=shape), jnp.float32) for _ in range(3))

    def loss_flash(q, k, v):
        out, _ = flash(q, k, v, is_causal=causal)
        return (out * out).sum()

    def loss_ref(q, k, v):
        out, _ = attention_with_lse(q, k, v, is_causal=causal)
        return (out * out).sum()

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g1, g2, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-4, rtol=1e-3,
            err_msg=f"d{name} mismatch",
        )


@pytest.mark.parametrize("lens", [[7, 64, 0, 33], [64, 64, 64, 64], [1, 2, 3, 4]])
def test_kv_len_ragged_masking(rng, lens):
    """Per-(batch,head) valid-key counts: forward, lse, and grads must match
    the jnp reference with the same kv_valid_len (incl. a zero-length row)."""
    B, L, H, D = 2, 64, 2, 16
    kv = np.asarray(lens, np.int32).reshape(B, H)
    q, k, v = (jnp.asarray(rng.normal(size=(B, L, H, D)), jnp.float32) for _ in range(3))
    out_p, lse_p = flash(q, k, v, kv_len=kv)
    out_j, lse_j = attention_with_lse(q, k, v, kv_valid_len=kv)
    np.testing.assert_allclose(np.asarray(out_p), np.asarray(out_j), atol=2e-5, rtol=1e-4)
    # lse is implementation-defined (~NEG_INF scale) on zero-valid rows;
    # both paths give such rows ~zero weight in the dilated branch fusion
    nonempty = (kv > 0)[:, :, None] * np.ones((B, H, L), bool)
    np.testing.assert_allclose(
        np.asarray(lse_p)[nonempty], np.asarray(lse_j)[nonempty], atol=2e-4, rtol=1e-4
    )

    def loss_p(q, k, v):
        o, _ = flash(q, k, v, kv_len=kv)
        return (o * o).sum()

    def loss_j(q, k, v):
        o, _ = attention_with_lse(q, k, v, kv_valid_len=kv)
        return (o * o).sum()

    g1 = jax.grad(loss_p, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_j, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g1, g2, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-4, rtol=1e-3, err_msg=f"d{name}"
        )


def test_kv_len_masks_large_real_keys(rng):
    """Masked key slots holding LARGE real activations (alignment padding
    becomes nonzero after residual layers) must not perturb outputs, lse, or
    gradients — a post-softmax zero-multiply would let them dominate the
    running max (underflowing valid rows) and produce inf*0 NaNs in the
    backward. Regression for the column-bias masking."""
    B, L, H, D = 1, 64, 2, 16
    n_valid = 40
    kv = np.full((B, H), n_valid, np.int32)
    q = jnp.asarray(rng.normal(size=(B, L, H, D)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, L, H, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, L, H, D)), jnp.float32)
    # masked tail keys are huge -> logits ~ +-40*|q| >> valid logits
    k = k.at[:, n_valid:].set(40.0)
    v = v.at[:, n_valid:].set(40.0)

    out_p, lse_p = flash(q, k, v, kv_len=kv)
    ref, lse_ref = attention_with_lse(
        q, k[:, :n_valid], v[:, :n_valid]
    )
    np.testing.assert_allclose(np.asarray(out_p), np.asarray(ref), atol=2e-5, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(lse_p), np.asarray(lse_ref), atol=2e-4, rtol=1e-4)

    def loss_p(q, k, v):
        o, _ = flash(q, k, v, kv_len=kv)
        return (o * o).sum()

    grads = jax.grad(loss_p, argnums=(0, 1, 2))(q, k, v)
    for g, name in zip(grads, "qkv"):
        assert np.isfinite(np.asarray(g)).all(), f"d{name} has NaN/inf"
    # masked key/value slots receive zero gradient
    np.testing.assert_allclose(np.asarray(grads[1][:, n_valid:]), 0.0, atol=1e-6)
    np.testing.assert_allclose(np.asarray(grads[2][:, n_valid:]), 0.0, atol=1e-6)


def test_unaligned_lengths(rng):
    """L not a multiple of the block size: padded keys must be masked."""
    q, k, v = (jnp.asarray(rng.normal(size=(1, 333, 2, 48)), jnp.float32) for _ in range(3))
    out, lse = flash(q, k, v)
    ref_out, ref_lse = attention_with_lse(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref_out), atol=2e-5, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(lse), np.asarray(ref_lse), atol=2e-5, rtol=1e-4)


def test_bwd_blocks_fit_budget():
    """The backward block pair must fit the backward's scoped-vmem budget.

    Regression for the BENCH_r03 crash: m=1281 (flagship r=8 branch at
    N=10241) picks a 1408 forward single block, and reusing it squared in
    the backward overflowed scoped vmem (20.12 MB vs the 16 MB limit)."""
    from gigapath_tpu.ops.dilated_attention import _bhld_geom
    from gigapath_tpu.ops.pallas_flash import _BWD_LOGITS_BUDGET, bwd_blocks

    # the exact crash geometry: flagship r=8 branch at N=10241
    *_rest, m, fwd_block = _bhld_geom(10241, 185363, 8)
    assert (m, fwd_block) == (1281, 1408)
    bq, bk = bwd_blocks(fwd_block)
    assert bq == 1408, "q side should keep the forward block (stays unpadded)"
    assert bq * bk <= _BWD_LOGITS_BUDGET
    # every forward block the adaptive dispatcher can emit stays in budget
    for fb in (128, 640, 768, 1024, 1280, 1408):
        bq, bk = bwd_blocks(fb)
        assert bq == fb and bk % 128 == 0
        assert bq * bk <= _BWD_LOGITS_BUDGET, (fb, bq, bk)


def test_bwd_impl_asymmetric_blocks_match(rng):
    """dq/dk/dv must be invariant to the (block_q, block_k) choice."""
    from gigapath_tpu.ops import pallas_flash as pf

    B, H, S, M, D = 1, 2, 2, 320, 16
    q, k, v = (
        jnp.asarray(rng.normal(size=(B, H, S, M, D)), jnp.float32)
        for _ in range(3)
    )
    do = jnp.asarray(rng.normal(size=(B, H, S, M, D)), jnp.float32)
    out, lse = pf._fwd_impl(q, k, v, None, False, D ** -0.5, 128, 128, True)
    delta = jnp.sum(do * out, axis=-1)

    ref = pf._bwd_impl(q, k, v, lse, delta, do, None, False, D ** -0.5, 128, 128, True)
    for bq, bk in ((256, 128), (128, 256), (320, 128)):
        got = pf._bwd_impl(
            q, k, v, lse, delta, do, None, False, D ** -0.5, bq, bk, True
        )
        for a, b, name in zip(got, ref, ("dq", "dk", "dv")):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-4,
                err_msg=f"{name} differs at blocks ({bq}, {bk})",
            )


def test_flat_bwd_resegment_fallback_matches(rng, monkeypatch):
    """The oversized-g flat backward (re-segment + generic kernels) must
    match the single-block flat backward on the valid region."""
    from gigapath_tpu.ops import pallas_flash as pf

    B, H, L, D, g, rl = 1, 2, 600, 16, 256, 580
    q, k, v = (
        jnp.asarray(rng.normal(size=(B, H, L, D)), jnp.float32)
        for _ in range(3)
    )

    def loss(q, k, v):
        out, _ = pf.flat_segment_flash(
            q, k, v, segment_len=g, real_len=rl, interpret=True
        )
        return (out[:, :, :rl] ** 2).sum()

    g_normal = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    # smallest legal budget that still forces the fallback at this g
    monkeypatch.setattr(pf, "_BWD_LOGITS_BUDGET", g * 128)
    g_fallback = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_fallback, g_normal, ("dq", "dk", "dv")):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-4,
            err_msg=f"{name} differs between fallback and flat backward",
        )


def test_flat_bwd_fallback_masks_invalid_row_cotangents(rng, monkeypatch):
    """A cotangent touching rows beyond real_len (out is garbage there by
    contract) must contribute nothing to dk/dv in the fallback — matching
    the flat=True kernels' qrow zeroing, so gradient semantics don't flip
    across the budget threshold."""
    from gigapath_tpu.ops import pallas_flash as pf

    B, H, L, D, g, rl = 1, 2, 600, 16, 256, 580
    q, k, v = (
        jnp.asarray(rng.normal(size=(B, H, L, D)), jnp.float32)
        for _ in range(3)
    )

    def loss(q, k, v):
        out, _ = pf.flat_segment_flash(
            q, k, v, segment_len=g, real_len=rl, interpret=True
        )
        return (out ** 2).sum()  # deliberately touches rows in [rl, L)

    g_normal = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    # smallest legal budget that still forces the fallback at this g
    monkeypatch.setattr(pf, "_BWD_LOGITS_BUDGET", g * 128)
    g_fallback = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    # dk/dv must agree everywhere; dq only on the valid region (invalid
    # rows' dq is garbage-on-garbage in the flat path, zero in the fallback)
    for a, b, name in zip(g_fallback[1:], g_normal[1:], ("dk", "dv")):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-4,
            err_msg=f"{name} differs with invalid-row cotangents",
        )
    np.testing.assert_allclose(
        np.asarray(g_fallback[0][:, :, :rl]), np.asarray(g_normal[0][:, :, :rl]),
        atol=1e-5, rtol=1e-4, err_msg="dq differs on the valid region",
    )
