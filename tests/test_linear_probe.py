"""Linear probe: end-to-end on a synthetic linearly-separable PCam-style zip.

Pins the reference recipe (``linear_probe/main.py:65-260``): cycled SGD +
cosine annealing on a single linear layer, eval-interval best-f1 selection,
results.txt artifact — and that the probe actually learns (AUROC ~ 1 on a
separable problem), the shape of the PCam AUC-parity north star.
"""

import io
import os
import zipfile

import numpy as np
import pandas as pd


def _make_pcam_fixture(tmp_path, rng, d=16, n_per_split=40):
    """Linearly separable 2-class embeddings in a zip + csv."""
    import torch

    w = rng.normal(size=d)
    zpath = tmp_path / "embeds.zip"
    names, labels, splits = [], [], []
    with zipfile.ZipFile(zpath, "w") as z:
        for split in ("train", "val", "test"):
            for i in range(n_per_split):
                x = rng.normal(size=d)
                label = "pos" if x @ w > 0 else "neg"
                name = f"{split}_{i}"
                buf = io.BytesIO()
                torch.save(torch.from_numpy(x.astype(np.float32)), buf)
                z.writestr(f"e/{name}.pt", buf.getvalue())
                names.append(name)
                labels.append(label)
                splits.append(split)
    csv = tmp_path / "ds.csv"
    pd.DataFrame({"input": names, "label": labels, "split": splits}).to_csv(csv)
    return str(csv), str(zpath)


def test_linear_probe_end_to_end(tmp_path, rng):
    from gigapath_tpu.linear_probe.main import main

    csv, zpath = _make_pcam_fixture(tmp_path, rng)
    out = str(tmp_path / "out")
    results = main(
        [
            "--dataset_csv", csv,
            "--input_path", zpath,
            "--embed_dim", "16",
            "--batch_size", "16",
            "--train_iters", "300",
            "--lr", "0.5",
            "--eval_interval", "100",
            "--seed", "0",
            "--report_to", "jsonl",
            "--output_dir", out,
        ]
    )
    assert results["test_auroc"] > 0.95  # separable -> near-perfect
    assert os.path.exists(os.path.join(out, "results.txt"))
    text = open(os.path.join(out, "results.txt")).read()
    assert "Test f1" in text and "Test AUROC" in text


def test_linear_probe_best_model_selection(tmp_path, rng):
    """best-f1 checkpoint is reloaded for test when model_select=best."""
    from gigapath_tpu.linear_probe.main import (
        init_linear_probe,
        train,
    )
    from gigapath_tpu.data.pcam import EmbeddingDataset

    csv, zpath = _make_pcam_fixture(tmp_path, rng)
    ds = [EmbeddingDataset(csv, zpath, split=s) for s in ("train", "val", "test")]
    params = init_linear_probe(16, 2, 0)
    res = train(
        params,
        *ds,
        train_iters=120,
        batch_size=16,
        lr=0.5,
        eval_interval=40,
        output_dir=str(tmp_path / "o2"),
        model_select="best",
        report_to="jsonl",
    )
    assert 0 <= res["val_f1"] <= 1 and res["test_f1"] > 0.8
