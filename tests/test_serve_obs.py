"""Serving latency observability (ISSUE 9 acceptance): typed metrics,
end-to-end request traces, and SLO burn-rate gating on the
queue -> bucket -> AOT -> cache path.

The pinned invariants:

- **exact histogram counts**: N served slides = N ``serve.e2e_s`` and N
  ``serve.queue_wait_s`` observations — under concurrent submitters too
  (nothing dropped or double-counted across the service lock);
- **traces nest**: every dispatched request's Chrome-trace spans
  (``submit -> queue -> dispatch[forward, cache_store]``) are contained
  in its ``request`` root on its own track, under ONE stable
  ``trace_id``;
- **slo_burn both ways**: a forced-slow-dispatch run (chaos
  ``slow_dispatch@*``) fires EXACTLY ONE ``slo_burn`` anomaly with the
  flight-dump + profiler-capture reactions; a clean run fires none;
- **zero overhead when off**: obs-off twin leaves no metrics/trace
  files, and the watched executable's HLO is byte-identical ON vs OFF
  with XLA-layer compile counts pinned equal.
"""

import glob
import json
import logging
import os
import sys

import jax
import numpy as np
import pytest

from gigapath_tpu.obs.metrics import MetricsRegistry, NullMetricsRegistry
from gigapath_tpu.obs.reqtrace import NullTraceCollector, TraceCollector
from gigapath_tpu.serve import ServeConfig, SlideService

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                    "scripts"),
)


@pytest.fixture(scope="module")
def tiny_model(serve_tiny_model):
    # the session-scoped shared serving model (conftest.py) — paying
    # the ~10 s flax init once per suite, not once per module
    return serve_tiny_model


@pytest.fixture
def rng():
    return np.random.default_rng(0)


def _forward_fn(model):
    def forward(p, embeds, coords, pad_mask):
        return model.apply({"params": p}, embeds, coords,
                           pad_mask=pad_mask, deterministic=True)

    return forward


def _config(tmp_path, **overrides):
    base = dict(
        max_batch=2, max_wait_s=0.01, bucket_min=16, bucket_growth=2.0,
        bucket_max=32, bucket_align=16, feature_dim=16, artifact_dir=None,
    )
    base.update(overrides)
    return ServeConfig(**base)


def _slides(rng, lengths):
    return [
        (f"s{i}_n{n}", rng.normal(size=(n, 16)).astype(np.float32),
         rng.uniform(0, 25000, (n, 2)).astype(np.float32))
        for i, n in enumerate(lengths)
    ]


def _events(path):
    with open(path, encoding="utf-8") as fh:
        return [json.loads(line) for line in fh if line.strip()]


class _XlaCompileCounter(logging.Handler):
    def __init__(self):
        super().__init__()
        self.count = 0

    def emit(self, record):
        if "Finished XLA compilation of" in record.getMessage():
            self.count += 1


class _count_xla_compiles:
    def __enter__(self):
        self.counter = _XlaCompileCounter()
        self.logger = logging.getLogger("jax._src.dispatch")
        self.prev_level = self.logger.level
        self.logger.addHandler(self.counter)
        self.logger.setLevel(logging.DEBUG)
        jax.config.update("jax_log_compiles", True)
        return self.counter

    def __exit__(self, *exc):
        jax.config.update("jax_log_compiles", False)
        self.logger.setLevel(self.prev_level)
        self.logger.removeHandler(self.counter)


# ---------------------------------------------------------------------------
# exact latency telemetry
# ---------------------------------------------------------------------------

class TestServiceMetrics:
    def test_histogram_counts_exact_sync(self, tiny_model, rng, tmp_path):
        model, params = tiny_model
        service = SlideService(
            _forward_fn(model), params, config=_config(tmp_path),
            out_dir=str(tmp_path), identity="tiny",
        )
        slides = _slides(rng, [5, 16, 17, 30])
        futs = [service.submit(*s) for s in slides]
        service.drain()
        for f in futs:
            f.result(timeout=60)
        snap = service.metrics.snapshot()
        hists = snap["histograms"]
        assert hists["serve.e2e_s"]["count"] == 4
        assert hists["serve.queue_wait_s"]["count"] == 4
        assert hists["serve.dispatch_s"]["count"] == service.dispatch_count
        assert snap["counters"]["serve.submits"] == 4.0
        assert snap["counters"]["serve.slides"] == 4.0
        assert snap["counters"]["serve.dispatches"] == service.dispatch_count
        # every latency is a real positive number
        assert hists["serve.e2e_s"]["min"] > 0
        assert hists["serve.e2e_s"]["p99"] >= hists["serve.e2e_s"]["p50"]
        run_path = service.runlog.path
        service.close()
        # final metrics event flushed inside run_end
        finals = [ev for ev in _events(run_path)
                  if ev["kind"] == "metrics" and ev["reason"] == "final"]
        assert len(finals) == 1
        assert finals[0]["histograms"]["serve.e2e_s"]["count"] == 4

    def test_cache_hits_and_joins_counted_not_double_observed(
        self, tiny_model, rng, tmp_path
    ):
        model, params = tiny_model
        service = SlideService(
            _forward_fn(model), params, config=_config(tmp_path),
            out_dir=str(tmp_path), identity="tiny",
        )
        sid, feats, coords = _slides(rng, [16])[0]
        f1 = service.submit(sid, feats, coords)
        service.drain()
        f1.result(timeout=60)
        f2 = service.submit("repeat_" + sid, feats, coords)  # cache hit
        assert np.allclose(np.asarray(f2.result(timeout=5)),
                           np.asarray(f1.result()))
        snap = service.metrics.snapshot()
        # the hit resolved without a forward: ONE e2e observation only
        assert snap["histograms"]["serve.e2e_s"]["count"] == 1
        assert snap["counters"]["serve.submits"] == 2.0
        assert snap["counters"]["serve.cache_hits"] == 1.0
        service.close()

    def test_concurrent_submitters_exact_counts(self, tiny_model, rng,
                                                tmp_path):
        """24 distinct slides from 8 threads through the worker: every
        observation lands exactly once (the service-lock satellite)."""
        from concurrent.futures import ThreadPoolExecutor

        model, params = tiny_model
        slides = _slides(rng, [5, 9, 16, 17, 20, 30] * 4)
        with SlideService(
            _forward_fn(model), params,
            config=_config(tmp_path, max_batch=3),
            out_dir=str(tmp_path), identity="tiny",
        ) as service:
            with ThreadPoolExecutor(max_workers=8) as pool:
                futs = list(pool.map(lambda s: service.submit(*s), slides))
            results = [f.result(timeout=120) for f in futs]
            assert len(results) == 24
            snap = service.metrics.snapshot()
            hist = snap["histograms"]["serve.e2e_s"]
            assert hist["count"] == 24, "dropped/double-counted e2e"
            assert sum(hist["counts"]) == 24
            assert snap["histograms"]["serve.queue_wait_s"]["count"] == 24
            assert snap["counters"]["serve.submits"] == 24.0
            assert snap["counters"]["serve.slides"] == 24.0


# ---------------------------------------------------------------------------
# request traces
# ---------------------------------------------------------------------------

class TestServiceTraces:
    def test_traces_nest_with_stable_ids_and_cache_store(
        self, tiny_model, rng, tmp_path
    ):
        model, params = tiny_model
        service = SlideService(
            _forward_fn(model), params, config=_config(tmp_path),
            out_dir=str(tmp_path), identity="tiny",
        )
        slides = _slides(rng, [5, 16, 17])
        futs = [service.submit(*s) for s in slides]
        service.drain()
        for f in futs:
            f.result(timeout=60)
        hit = service.submit("rehit", slides[0][1], slides[0][2])
        hit.result(timeout=5)
        run_path = service.runlog.path
        service.close()  # run_end -> closers -> export

        trace_path = os.path.splitext(run_path)[0] + ".trace.json"
        assert os.path.exists(trace_path)
        doc = json.load(open(trace_path))
        by_tid = {}
        for ev in doc["traceEvents"]:
            if ev.get("ph") == "X":
                by_tid.setdefault(ev["tid"], []).append(ev)
        assert len(by_tid) == 4  # 3 dispatched + 1 cache-hit request
        full_chains = 0
        hit_tracks = 0
        for tid, evs in by_tid.items():
            roots = [e for e in evs if e["name"] == "request"]
            assert len(roots) == 1
            root = roots[0]
            lo, hi = root["ts"], root["ts"] + root["dur"]
            assert {e["args"]["trace_id"] for e in evs} == {
                root["args"]["trace_id"]
            }, "span escaped its trace_id"
            names = {e["name"] for e in evs}
            if {"submit", "queue", "dispatch", "forward",
                    "cache_store"} <= names:
                full_chains += 1
                for e in evs:
                    assert lo - 0.5 <= e["ts"]
                    assert e["ts"] + e["dur"] <= hi + 0.5, (
                        f"{e['name']} escapes its request"
                    )
                # chronological chain: submit ends before queue ends
                # before dispatch ends
                end = {e["name"]: e["ts"] + e["dur"] for e in evs}
                assert end["submit"] <= end["queue"] <= end["dispatch"]
            elif root["args"]["status"] == "cache_hit":
                hit_tracks += 1
        assert full_chains == 3 and hit_tracks == 1
        # the trace event landed on the run log
        trace_events = [ev for ev in _events(run_path)
                        if ev["kind"] == "trace"]
        assert len(trace_events) == 1
        assert trace_events[0]["traces"] == 4


# ---------------------------------------------------------------------------
# SLO burn: the closed loop, both ways
# ---------------------------------------------------------------------------

def _slo_config(tmp_path, target_s):
    return _config(
        tmp_path, bucket_max=16, slo_target_s=target_s, slo_budget=0.25,
        slo_burn_threshold=1.5, slo_short_window_s=30.0,
        slo_long_window_s=60.0, slo_min_events=4,
    )


class TestSloBurn:
    def test_forced_slow_dispatch_fires_exactly_one_slo_burn(
        self, tiny_model, rng, tmp_path, monkeypatch
    ):
        monkeypatch.delenv("GIGAPATH_OBS", raising=False)
        monkeypatch.delenv("GIGAPATH_ANOMALY", raising=False)
        monkeypatch.setenv("GIGAPATH_CHAOS", "slow_dispatch@*:0.05")
        model, params = tiny_model
        service = SlideService(
            _forward_fn(model), params,
            config=_slo_config(tmp_path, target_s=0.01),
            out_dir=str(tmp_path), identity="tiny",
        )
        slides = _slides(rng, [5, 7, 9, 11])  # one bucket, 4 requests
        futs = [service.submit(*s) for s in slides]
        service.drain()
        for f in futs:
            f.result(timeout=60)
        run_path = service.runlog.path
        service.close()
        events = _events(run_path)
        burns = [ev for ev in events if ev.get("kind") == "anomaly"
                 and ev.get("detector") == "slo_burn"]
        assert len(burns) == 1, (
            f"want exactly one slo_burn, got {len(burns)}"
        )
        # the reactions: flight dump written, profiler capture armed
        assert burns[0]["flight"] and os.path.exists(burns[0]["flight"])
        assert burns[0]["trace_dir"] and os.path.isdir(burns[0]["trace_dir"])
        # the transition slo event that fed the detector
        slos = [ev for ev in events if ev.get("kind") == "slo"]
        assert any(ev.get("burning") and not ev.get("final") for ev in slos)

    def test_deadline_failures_burn_the_slo(self, tiny_model, rng,
                                            tmp_path, monkeypatch):
        """A deadline storm produces zero successful latencies — the
        failures themselves must reach the tracker as violations."""
        import time as _time

        monkeypatch.delenv("GIGAPATH_OBS", raising=False)
        monkeypatch.delenv("GIGAPATH_CHAOS", raising=False)
        model, params = tiny_model
        config = _slo_config(tmp_path, target_s=0.01)
        config = ServeConfig(**{**config.__dict__, "deadline_s": 0.001})
        service = SlideService(
            _forward_fn(model), params, config=config,
            out_dir=str(tmp_path), identity="tiny",
        )
        futs = [service.submit(*s) for s in _slides(rng, [5, 7, 9, 11])]
        _time.sleep(0.05)  # every request is now past its deadline
        service.drain()
        for f in futs:
            with pytest.raises(Exception):
                f.result(timeout=10)
        assert service.slo.violations == 4 and service.slo.total == 4
        service.close()

    def test_clean_run_fires_none_and_final_status_lands(
        self, tiny_model, rng, tmp_path, monkeypatch
    ):
        monkeypatch.delenv("GIGAPATH_OBS", raising=False)
        monkeypatch.delenv("GIGAPATH_ANOMALY", raising=False)
        monkeypatch.delenv("GIGAPATH_CHAOS", raising=False)
        model, params = tiny_model
        service = SlideService(
            _forward_fn(model), params,
            config=_slo_config(tmp_path, target_s=300.0),
            out_dir=str(tmp_path), identity="tiny",
        )
        slides = _slides(rng, [5, 7, 9, 11, 13, 15])
        futs = [service.submit(*s) for s in slides]
        service.drain()
        for f in futs:
            f.result(timeout=60)
        run_path = service.runlog.path
        service.close()
        events = _events(run_path)
        assert not [ev for ev in events if ev.get("kind") == "anomaly"
                    and ev.get("detector") == "slo_burn"]
        finals = [ev for ev in events if ev.get("kind") == "slo"
                  and ev.get("final")]
        assert len(finals) == 1 and finals[0]["burning"] is False
        assert finals[0]["violations"] == 0 and finals[0]["total"] == 6


# ---------------------------------------------------------------------------
# overhead invariants: metrics+tracing ON vs OFF
# ---------------------------------------------------------------------------

class TestOverheadInvariants:
    def test_obs_off_twin_no_metrics_no_traces_no_slo(
        self, tiny_model, rng, tmp_path, monkeypatch
    ):
        monkeypatch.setenv("GIGAPATH_OBS", "0")
        model, params = tiny_model
        service = SlideService(
            _forward_fn(model), params,
            config=_slo_config(tmp_path, target_s=0.01),
            out_dir=str(tmp_path), identity="tiny",
        )
        assert isinstance(service.metrics, NullMetricsRegistry)
        assert not isinstance(service.metrics, MetricsRegistry)
        assert isinstance(service.tracer, NullTraceCollector)
        assert not isinstance(service.tracer, TraceCollector)
        fut = service.submit("s", rng.normal(size=(5, 16)).astype(np.float32))
        service.drain()
        assert np.isfinite(np.asarray(fut.result(timeout=60))).all()
        service.close()
        assert not os.path.exists(tmp_path / "obs")
        assert not glob.glob(str(tmp_path / "**" / "*.trace.json"),
                             recursive=True)
        assert not glob.glob(str(tmp_path / "**" / "*.prom"),
                             recursive=True)

    def test_watched_hlo_byte_identical_and_compile_counts_pinned(
        self, tiny_model, rng, tmp_path, monkeypatch
    ):
        """The instrumented service's compiled executable is the SAME
        program as the obs-off twin's (HLO text byte-equal), and both
        pay exactly one XLA compile for one bucket."""
        model, params = tiny_model
        feats = rng.normal(size=(5, 16)).astype(np.float32)

        def serve_one(obs_on, out_dir):
            if obs_on:
                monkeypatch.delenv("GIGAPATH_OBS", raising=False)
            else:
                monkeypatch.setenv("GIGAPATH_OBS", "0")
            service = SlideService(
                _forward_fn(model), params,
                config=_config(tmp_path, bucket_max=16),
                out_dir=out_dir, identity="tiny",
            )
            with _count_xla_compiles() as counter:
                fut = service.submit("s", feats)
                service.drain()
                fut.result(timeout=60)
            key = (service.capacity_for(16), 16)
            hlo = service.aot._executables[key].as_text()
            service.close()
            return hlo, counter.count

        hlo_on, compiles_on = serve_one(True, str(tmp_path / "on"))
        hlo_off, compiles_off = serve_one(False, str(tmp_path / "off"))
        assert hlo_on == hlo_off, "obs instrumentation changed the program"
        assert compiles_on == compiles_off == 1


# ---------------------------------------------------------------------------
# the smoke script's PR-9 surface (in-process, small scale)
# ---------------------------------------------------------------------------

class TestServeSmokeLatencySurface:
    def _run(self, tmp_path, extra):
        import serve_smoke

        json_path = str(tmp_path / "SERVE_SMOKE.json")
        prev_chaos = os.environ.get("GIGAPATH_CHAOS")
        try:
            rc = serve_smoke.main([
                "--out-dir", str(tmp_path / "out"), "--json", json_path,
                "--slides", "6", "--distinct-lengths", "3", "--repeats", "3",
                "--threads", "3", "--max-batch", "2", "--bucket-max", "32",
            ] + extra)
        finally:
            # in-process main(): the forced-slow path appends to
            # GIGAPATH_CHAOS — restore so later tests see a clean env
            if prev_chaos is None:
                os.environ.pop("GIGAPATH_CHAOS", None)
            else:
                os.environ["GIGAPATH_CHAOS"] = prev_chaos
        with open(json_path) as fh:
            return rc, json.load(fh)

    def test_clean_smoke_emits_metrics_trace_and_no_burn(self, tmp_path,
                                                         monkeypatch):
        monkeypatch.delenv("GIGAPATH_OBS", raising=False)
        monkeypatch.delenv("GIGAPATH_ANOMALY", raising=False)
        rc, payload = self._run(tmp_path, ["--slo-target-s", "300"])
        assert rc == 0, payload
        hists = payload["metrics"]["histograms"]
        for name in ("serve.queue_wait_s", "serve.dispatch_s",
                     "serve.e2e_s"):
            assert hists[name]["count"] > 0
            for q in ("p50", "p90", "p99"):
                assert hists[name][q] is not None
        for key in ("e2e_p50_s", "e2e_p90_s", "e2e_p99_s",
                    "dispatch_p50_s", "dispatch_p99_s", "queue_wait_p99_s"):
            assert isinstance(payload[key], float)
        assert payload["slo_burn_anomalies"] == 0
        assert os.path.exists(payload["trace_json"])
        assert payload["trace_nested_requests"] == 6

    def test_forced_slow_smoke_fires_exactly_one_burn(self, tmp_path,
                                                      monkeypatch):
        monkeypatch.delenv("GIGAPATH_OBS", raising=False)
        monkeypatch.delenv("GIGAPATH_ANOMALY", raising=False)
        rc, payload = self._run(tmp_path, [
            "--slo-target-s", "0.05", "--slow-dispatch-s", "0.2",
            "--no-warm-restart",
        ])
        assert rc == 0, payload
        assert payload["slo_burn_anomalies"] == 1
        assert os.path.exists(payload["slo_burn_flight"])
        assert os.path.isdir(payload["slo_burn_trace_dir"])

    def test_obs_off_smoke_twin_leaves_no_latency_surface(self, tmp_path,
                                                          monkeypatch):
        monkeypatch.setenv("GIGAPATH_OBS", "0")
        rc, payload = self._run(tmp_path, [])
        assert rc == 0, payload
        assert "metrics" not in payload
        assert "trace_json" not in payload
        assert payload["obs"] is None
        assert not glob.glob(str(tmp_path / "out" / "**" / "*.trace.json"),
                             recursive=True)
        assert not os.path.exists(tmp_path / "out" / "obs")
