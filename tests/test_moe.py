"""MoE subsystem: gating semantics, MOELayer, expert parallelism.

The reference ships the xmoe stack wired but config-off (moe_freq: 0 in every
LongNet config) and entirely untested; here every property is pinned:
capacity-limited top-1/top-2 routing, the GShard balance loss, dispatch /
combine einsum algebra, per-expert distinct init, GSPMD expert sharding
equivalence on the 8-device CPU mesh, the explicit all_to_all choreography,
and an MoE LongNet encoder training one step with l_aux in the loss.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from gigapath_tpu.architecture.config import EncoderConfig
from gigapath_tpu.ops.moe.moe_layer import MOELayer
from gigapath_tpu.ops.moe.routing import top1_gating, top2_gating


def _logits(rng, S, E):
    return jnp.asarray(rng.normal(size=(S, E)), jnp.float32)


class TestTop1Gating:
    def test_routes_to_argmax_until_capacity(self, rng):
        S, E = 8, 2
        logits = _logits(rng, S, E)
        l_aux, combine, dispatch, meta = top1_gating(logits, capacity_factor=1.0)
        capacity = int(np.ceil(S / E))  # 4
        assert combine.shape == (S, E, capacity)
        # each expert receives at most `capacity` tokens
        per_expert = np.asarray(dispatch).sum(axis=(0, 2))
        assert (per_expert <= capacity).all()
        # tokens that were dispatched went to their argmax expert
        gates = jax.nn.softmax(logits, axis=-1)
        top = np.asarray(jnp.argmax(gates, axis=-1))
        routed = np.asarray(dispatch).sum(axis=2)  # [S, E]
        for s in range(S):
            if routed[s].sum() > 0:
                assert routed[s, top[s]] == 1
        # combine weight of a routed token equals its top gate prob
        for s in range(S):
            if routed[s].sum() > 0:
                np.testing.assert_allclose(
                    float(np.asarray(combine)[s].sum()),
                    float(gates[s, top[s]]),
                    rtol=1e-5,
                )
        assert np.isfinite(float(l_aux))
        assert "entropy_gating" in meta and "unused_expert1_count" in meta

    def test_capacity_ordering_first_come_first_served(self):
        # 3 tokens all preferring expert 0, capacity 1 x ceil(3/3)=1:
        # only the first token in sequence order is kept
        logits = jnp.asarray(
            [[5.0, 0.0, 0.0], [5.0, 0.0, 0.0], [5.0, 0.0, 0.0]], jnp.float32
        )
        _, _, dispatch, _ = top1_gating(logits, capacity_factor=1.0)
        routed = np.asarray(dispatch).sum(axis=(1, 2))
        np.testing.assert_array_equal(routed, [1, 0, 0])

    def test_l_aux_uniform_vs_collapsed(self, rng):
        S, E = 32, 4
        # perfectly balanced one-hot routing -> l_aux ~ 1; collapsed -> ~ E
        balanced = jnp.eye(E, dtype=jnp.float32)[jnp.arange(S) % E] * 10
        collapsed = jnp.zeros((S, E)).at[:, 0].set(10.0)
        l_b = float(top1_gating(balanced)[0])
        l_c = float(top1_gating(collapsed)[0])
        assert l_b < l_c
        assert l_c == pytest.approx(E * (1 / E) * 1.0 * E, rel=0.1)  # ~E

    def test_input_mask_drops_padding(self, rng):
        S, E = 8, 2
        logits = _logits(rng, S, E)
        mask = jnp.zeros(S, bool).at[4:].set(True)
        _, _, dispatch, _ = top1_gating(logits, input_mask=mask)
        routed = np.asarray(dispatch).sum(axis=(1, 2))
        assert (routed[4:] == 0).all()

    def test_eval_capacity_fraction(self, rng):
        S, E = 16, 2
        logits = _logits(rng, S, E)
        _, combine, _, _ = top1_gating(
            logits, eval_mode=True, eval_capacity_token_fraction=0.25
        )
        assert combine.shape[-1] == int(np.ceil(0.25 * S))


class TestTop2Gating:
    def test_two_experts_combine_normalized(self, rng):
        S, E = 8, 4
        logits = _logits(rng, S, E)
        l_aux, combine, dispatch, meta = top2_gating(logits)
        # every token that kept both slots has combine weights summing to 1
        c = np.asarray(combine).sum(axis=(1, 2))
        routed2 = np.asarray(dispatch).sum(axis=(1, 2)) == 2
        np.testing.assert_allclose(c[routed2], 1.0, rtol=1e-5)
        assert combine.shape[-1] == 2 * int(np.ceil(S / E))

    def test_second_expert_differs_from_first(self, rng):
        S, E = 16, 4
        logits = _logits(rng, S, E)
        _, _, dispatch, _ = top2_gating(logits)
        routed = np.asarray(dispatch).sum(axis=2)  # [S, E]
        assert (routed.sum(axis=1) <= 2).all()
        # no expert got the same token twice
        assert (routed <= 1).all()

    def test_sampling_policy_uses_rng(self, rng):
        S, E = 32, 4
        logits = _logits(rng, S, E)
        out1 = top2_gating(logits, rng=jax.random.PRNGKey(0), second_expert_policy="sampling")
        out2 = top2_gating(logits, rng=jax.random.PRNGKey(1), second_expert_policy="sampling")
        # different gumbel draws can change second-expert choices
        assert not np.array_equal(np.asarray(out1[2]), np.asarray(out2[2])) or True
        # deterministic (no rng) is reproducible
        a = top2_gating(logits)[1]
        b = top2_gating(logits)[1]
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_batch_prioritized_routing_prefers_confident(self):
        # expert 0, capacity 2*ceil(4/2)=4 -> no drop at S=4; shrink capacity
        # via eval mode: fraction 0.25 -> capacity 1. The most confident
        # token (last) wins the single slot under prioritized routing.
        logits = jnp.asarray(
            [[1.0, 0.0], [2.0, 0.0], [3.0, 0.0], [9.0, 0.0]], jnp.float32
        )
        _, _, disp_fifo, _ = top2_gating(
            logits, eval_mode=True, eval_capacity_token_fraction=0.25
        )
        _, _, disp_prio, _ = top2_gating(
            logits,
            eval_mode=True,
            eval_capacity_token_fraction=0.25,
            batch_prioritized_routing=True,
        )
        fifo_first = np.asarray(disp_fifo)[:, 0, :].sum(axis=1)
        prio_first = np.asarray(disp_prio)[:, 0, :].sum(axis=1)
        assert fifo_first[0] == 1  # sequence order wins
        assert prio_first[3] == 1  # confidence order wins


class TestMOELayer:
    def _layer(self, **kw):
        defaults = dict(embed_dim=16, ffn_dim=32, num_experts=4, top1=True)
        return MOELayer(**{**defaults, **kw})

    def test_forward_shapes_and_l_aux(self, rng):
        layer = self._layer()
        x = jnp.asarray(rng.normal(size=(2, 8, 16)), jnp.float32)
        params = layer.init(jax.random.PRNGKey(0), x)["params"]
        out, l_aux = layer.apply({"params": params}, x)
        assert out.shape == x.shape
        assert np.isfinite(float(l_aux))

    def test_experts_have_distinct_init(self, rng):
        layer = self._layer()
        x = jnp.asarray(rng.normal(size=(1, 8, 16)), jnp.float32)
        params = layer.init(jax.random.PRNGKey(0), x)["params"]
        k = np.asarray(params["experts"]["fc1"]["kernel"])  # [E, in, out]
        assert k.shape[0] == 4
        for e in range(1, 4):
            assert not np.allclose(k[0], k[e])

    def test_output_is_convex_expert_mix(self, rng):
        """With identity experts the layer reproduces gate-weighted input."""
        layer = self._layer(num_experts=2, top1=True)
        x = jnp.asarray(rng.normal(size=(1, 4, 16)), jnp.float32)
        params = layer.init(jax.random.PRNGKey(0), x)["params"]
        out, _ = layer.apply({"params": params}, x)
        # not identity (random experts), but differentiable and bounded
        g = jax.grad(
            lambda p: layer.apply({"params": p}, x)[0].sum()
        )(params)
        assert all(np.isfinite(np.asarray(v)).all() for v in jax.tree.leaves(g))

    def test_top2_layer_with_dropout_rng(self, rng):
        layer = self._layer(top1=False, second_expert_policy="sampling")
        x = jnp.asarray(rng.normal(size=(1, 8, 16)), jnp.float32)
        params = layer.init(jax.random.PRNGKey(0), x)["params"]
        out, l_aux = layer.apply(
            {"params": params},
            x,
            None,
            False,  # deterministic=False
            rngs={"dropout": jax.random.PRNGKey(7)},
        )
        assert out.shape == x.shape

    def test_metadata_sowed(self, rng):
        layer = self._layer()
        x = jnp.asarray(rng.normal(size=(1, 8, 16)), jnp.float32)
        params = layer.init(jax.random.PRNGKey(0), x)["params"]
        (_, _), mods = layer.apply(
            {"params": params}, x, mutable=["intermediates"]
        )
        meta = mods["intermediates"]["moe_metadata"][0]
        assert "entropy_gating" in meta

    def test_from_config(self):
        cfg = EncoderConfig(
            encoder_embed_dim=16,
            encoder_ffn_embed_dim=32,
            moe_freq=2,
            moe_expert_count=4,
            moe_top1_expert=True,
        )
        layer = MOELayer.from_config(cfg)
        assert layer.num_experts == 4 and layer.embed_dim == 16


class TestExpertParallel:
    def test_gspmd_expert_sharding_matches_single_device(self, rng):
        """MOELayer under an expert-sharded mesh == unsharded outputs."""
        from gigapath_tpu.parallel.mesh import make_mesh
        from gigapath_tpu.parallel.sharding import apply_shardings

        layer = MOELayer(embed_dim=16, ffn_dim=32, num_experts=8, top1=True)
        x = jnp.asarray(rng.normal(size=(2, 16, 16)), jnp.float32)
        params = layer.init(jax.random.PRNGKey(0), x)["params"]
        ref_out, ref_aux = jax.jit(
            lambda p, x: layer.apply({"params": p}, x)
        )(params, x)

        mesh = make_mesh(8, axis_sizes={"expert": 8})
        with mesh:
            sharded = apply_shardings(params, mesh)
            k = sharded["experts"]["fc1"]["kernel"]
            assert "expert" in str(k.sharding.spec)
            out, aux = jax.jit(lambda p, x: layer.apply({"params": p}, x))(
                sharded, x
            )
        np.testing.assert_allclose(
            np.asarray(ref_out), np.asarray(out), atol=1e-5
        )
        np.testing.assert_allclose(float(ref_aux), float(aux), rtol=1e-5)

    def test_shard_map_all_to_all_matches_serial(self, rng):
        """Explicit a2a choreography == per-shard serial computation."""
        from gigapath_tpu.ops.moe.expert_parallel import moe_expert_parallel
        from gigapath_tpu.parallel.mesh import make_mesh

        E, D, S_loc, M, F = 8, 4, 8, 16, 32
        S = D * S_loc
        mesh = make_mesh(D, axis_sizes={"expert": 4})
        tokens = jnp.asarray(rng.normal(size=(S, M)), jnp.float32)
        wg = jnp.asarray(rng.normal(size=(M, E)) * 0.1, jnp.float32)
        w1 = jnp.asarray(rng.normal(size=(E, M, F)) * 0.1, jnp.float32)
        w2 = jnp.asarray(rng.normal(size=(E, F, M)) * 0.1, jnp.float32)

        def gate_fn(toks):
            return top1_gating(toks @ wg)

        def expert_fn_pair(p, dispatched):  # [E_loc, C, M]
            w1_, w2_ = p
            return jax.vmap(lambda a, b, d: jax.nn.gelu(d @ a) @ b)(
                w1_, w2_, dispatched
            )

        out, l_aux = moe_expert_parallel(
            mesh, gate_fn, expert_fn_pair, (w1, w2), tokens
        )

        # serial reference: same per-shard gating + all experts available
        outs = []
        auxes = []
        for d in range(D):
            t = tokens[d * S_loc : (d + 1) * S_loc]
            aux_d, combine, dispatch, _ = gate_fn(t)
            disp = jnp.einsum("sec,sm->ecm", dispatch.astype(t.dtype), t)
            eo = jax.vmap(lambda a, b, x: jax.nn.gelu(x @ a) @ b)(w1, w2, disp)
            outs.append(jnp.einsum("sec,ecm->sm", combine.astype(t.dtype), eo))
            auxes.append(aux_d)
        ref = jnp.concatenate(outs, axis=0)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)
        np.testing.assert_allclose(
            float(l_aux), float(jnp.mean(jnp.stack(auxes))), rtol=1e-5
        )


class TestMoEEncoder:
    def test_moe_longnet_encoder_trains_one_step(self, rng):
        """Encoder with moe_freq=2 runs fwd+bwd with l_aux in the loss."""
        from gigapath_tpu.architecture.encoder import Encoder
        from gigapath_tpu.parallel.spmd import collect_moe_l_aux

        cfg = EncoderConfig(
            encoder_embed_dim=16,
            encoder_attention_heads=2,
            encoder_ffn_embed_dim=32,
            encoder_layers=2,
            moe_freq=2,
            moe_expert_count=4,
            moe_top1_expert=True,
            vocab_size=-1,
            no_output_layer=True,
        )
        enc = Encoder(cfg)
        x = jnp.asarray(rng.normal(size=(2, 8, 16)), jnp.float32)
        params = enc.init(jax.random.PRNGKey(0), token_embeddings=x)["params"]

        def loss_fn(p):
            out, mods = enc.apply(
                {"params": p},
                token_embeddings=x,
                mutable=["intermediates"],
            )
            l_aux = collect_moe_l_aux(mods["intermediates"])
            return out["encoder_out"].sum() * 0 + out["encoder_out"].var() + 0.01 * l_aux

        loss, grads = jax.value_and_grad(loss_fn)(params)
        assert np.isfinite(float(loss))
        # gate + expert params receive gradients
        gk = grads["layers_1"]["moe_layer"]["gate"]["wg"]["kernel"]
        assert np.abs(np.asarray(gk)).sum() > 0
        ek = grads["layers_1"]["moe_layer"]["experts"]["fc1"]["kernel"]
        assert np.isfinite(np.asarray(ek)).all()

    def test_train_step_moe_aux_weight(self, rng):
        """make_train_step(moe_aux_loss_weight=...) changes the loss."""
        from gigapath_tpu.models.classification_head import ClassificationHead
        from gigapath_tpu.parallel.spmd import make_train_step

        model = ClassificationHead(
            input_dim=32,
            latent_dim=64,
            feat_layer="1",
            n_classes=3,
            slide_kwargs=dict(
                embed_dim=64,
                depth=1,
                segment_length=[8, 16],
                dilated_ratio="[1, 2]",
                dropout=0.0,
                drop_path_rate=0.0,
            ),
        )
        B, N = 2, 16
        x = jnp.asarray(rng.normal(size=(B, N, 32)), jnp.float32)
        coords = jnp.asarray(rng.uniform(0, 25000, (B, N, 2)), jnp.float32)
        batch = {"images": x, "coords": coords, "labels": jnp.asarray([0, 2])}
        params = model.init(jax.random.PRNGKey(0), x, coords)["params"]
        opt = optax.adamw(1e-3)
        step0 = make_train_step(model, opt)
        step1 = make_train_step(model, opt, moe_aux_loss_weight=0.01)
        _, _, loss0 = step0(params, opt.init(params), batch, jax.random.PRNGKey(1))
        _, _, loss1 = step1(params, opt.init(params), batch, jax.random.PRNGKey(1))
        # no MoE layers in this model: weights agree (aux sum is 0)
        np.testing.assert_allclose(float(loss0), float(loss1), rtol=1e-6)
