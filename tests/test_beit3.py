"""Embedding components, multiway encoder integration, BEiT-3.

Pins: conv patch embedding shapes and mask-token substitution, fairseq
position offset, the multiway A/B split actually routing tokens through
different parameters, and BEiT-3 end-to-end over text / vision / fused
inputs (reference ``torchscale/model/BEiT3.py``, ``component/embedding.py``,
``component/multiway_network.py``).
"""

import jax
import jax.numpy as jnp
import numpy as np

from gigapath_tpu.architecture.config import EncoderConfig
from gigapath_tpu.models.beit3 import BEiT3
from gigapath_tpu.ops.embedding import (
    PositionalEmbedding,
    TextEmbedding,
    VisionEmbedding,
)
from gigapath_tpu.ops.multiway import MultiwayNetwork
from flax import linen as nn


class TestVisionEmbedding:
    def test_patch_count_and_cls(self, rng):
        ve = VisionEmbedding(
            img_size=32, patch_size=16, embed_dim=24, prepend_cls_token=True,
            contain_mask_token=True,
        )
        x = jnp.asarray(rng.normal(size=(2, 32, 32, 3)), jnp.float32)
        params = ve.init(jax.random.PRNGKey(0), x)["params"]
        out = ve.apply({"params": params}, x)
        assert out.shape == (2, 5, 24)  # 4 patches + cls
        assert ve.num_position_embeddings() == 5

    def test_mask_token_substitution(self, rng):
        ve = VisionEmbedding(
            img_size=32, patch_size=16, embed_dim=24, contain_mask_token=True
        )
        x = jnp.asarray(rng.normal(size=(1, 32, 32, 3)), jnp.float32)
        params = ve.init(jax.random.PRNGKey(0), x)["params"]
        params = jax.tree.map(lambda v: v, params)
        params["mask_token"] = params["mask_token"] + 7.0
        masked = jnp.asarray([[1, 0, 0, 0]], jnp.int32)
        out = ve.apply({"params": params}, x, masked)
        ref = ve.apply({"params": params}, x)
        np.testing.assert_allclose(np.asarray(out[0, 0]), 7.0, atol=1e-5)
        np.testing.assert_allclose(
            np.asarray(out[0, 1:]), np.asarray(ref[0, 1:]), atol=1e-6
        )


def test_positional_embedding_fairseq_offset(rng):
    pe = PositionalEmbedding(10, 8)
    x = jnp.zeros((1, 3, 8))
    params = pe.init(jax.random.PRNGKey(0), x)["params"]
    out = pe.apply({"params": params}, x)
    table = np.asarray(params["weight"]["embedding"])
    np.testing.assert_allclose(np.asarray(out[0]), table[2:5], atol=1e-6)


def test_text_embedding_init_std(rng):
    te = TextEmbedding(1000, 64)
    params = te.init(jax.random.PRNGKey(0), jnp.zeros((1, 2), jnp.int32))["params"]
    w = np.asarray(params["weight"]["embedding"])
    assert abs(w.std() - 64**-0.5) / 64**-0.5 < 0.1


class TestMultiwayEncoder:
    def _cfg(self):
        return EncoderConfig(
            encoder_embed_dim=32,
            encoder_attention_heads=4,
            encoder_ffn_embed_dim=64,
            encoder_layers=2,
            multiway=True,
            vocab_size=-1,
            no_output_layer=True,
            dropout=0.0,
            drop_path_rate=0.0,
        )

    def test_split_routes_through_distinct_params(self, rng):
        from gigapath_tpu.architecture.encoder import Encoder

        enc = Encoder(self._cfg())
        x = jnp.asarray(rng.normal(size=(1, 8, 32)), jnp.float32)
        params = enc.init(
            jax.random.PRNGKey(0), token_embeddings=x, multiway_split_position=4
        )["params"]
        # A and B branches exist for ffn and projections
        ffn = params["layers_0"]["ffn"]
        assert "A" in ffn and "B" in ffn
        out_full_a = enc.apply(
            {"params": params}, token_embeddings=x, multiway_split_position=-1
        )["encoder_out"]
        out_split = enc.apply(
            {"params": params}, token_embeddings=x, multiway_split_position=4
        )["encoder_out"]
        # branch B differs from branch A -> the text half changes
        assert not np.allclose(np.asarray(out_full_a[:, 4:]), np.asarray(out_split[:, 4:]))

    def test_split_zero_uses_branch_b_everywhere(self, rng):
        """split=0 output == output of a param tree whose A branches were
        overwritten with B (i.e. genuinely routed through B)."""
        from gigapath_tpu.architecture.encoder import Encoder

        enc = Encoder(self._cfg())
        x = jnp.asarray(rng.normal(size=(1, 6, 32)), jnp.float32)
        params = enc.init(
            jax.random.PRNGKey(0), token_embeddings=x, multiway_split_position=3
        )["params"]
        out0 = enc.apply(
            {"params": params}, token_embeddings=x, multiway_split_position=0
        )["encoder_out"]

        def b_into_a(tree):
            if isinstance(tree, dict):
                if set(tree.keys()) >= {"A", "B"}:
                    tree = dict(tree, A=tree["B"])
                return {k: b_into_a(v) for k, v in tree.items()}
            return tree

        out_a = enc.apply(
            {"params": b_into_a(params)}, token_embeddings=x, multiway_split_position=-1
        )["encoder_out"]
        np.testing.assert_allclose(np.asarray(out0), np.asarray(out_a), atol=1e-5)


class TestBEiT3:
    def _model(self):
        cfg = EncoderConfig(
            encoder_embed_dim=32,
            encoder_attention_heads=4,
            encoder_ffn_embed_dim=64,
            encoder_layers=2,
            multiway=True,
            vocab_size=100,
            img_size=32,
            patch_size=16,
            dropout=0.0,
            drop_path_rate=0.0,
        )
        return BEiT3(cfg)

    def test_fused_vision_language(self, rng):
        model = self._model()
        text = jnp.asarray(rng.integers(0, 100, (2, 6)), jnp.int32)
        image = jnp.asarray(rng.normal(size=(2, 32, 32, 3)), jnp.float32)
        params = model.init(jax.random.PRNGKey(0), text, image)["params"]
        out = model.apply({"params": params}, text, image)
        assert out["encoder_out"].shape == (2, 5 + 6, 32)
        assert out["multiway_split_position"] == 5

    def test_single_modality(self, rng):
        model = self._model()
        text = jnp.asarray(rng.integers(0, 100, (2, 6)), jnp.int32)
        image = jnp.asarray(rng.normal(size=(2, 32, 32, 3)), jnp.float32)
        params = model.init(jax.random.PRNGKey(0), text, image)["params"]
        out_t = model.apply({"params": params}, text, None)
        assert out_t["encoder_out"].shape == (2, 6, 32)
        out_v = model.apply({"params": params}, None, image)
        assert out_v["encoder_out"].shape == (2, 5, 32)

    def test_single_modality_init_builds_full_tree(self, rng):
        """init with text only must still create vision + both multiway
        branches, so later fused calls work."""
        model = self._model()
        text = jnp.asarray(rng.integers(0, 100, (2, 6)), jnp.int32)
        image = jnp.asarray(rng.normal(size=(2, 32, 32, 3)), jnp.float32)
        params = model.init(jax.random.PRNGKey(0), text, None)["params"]
        assert "vision_embed" in params
        assert set(params["encoder"]["layers_0"]["ffn"]) >= {"A", "B"}
        out = model.apply({"params": params}, text, image)
        assert np.isfinite(np.asarray(out["encoder_out"])).all()

    def test_explicit_positions_with_fused_input(self, rng):
        model = self._model()
        text = jnp.asarray(rng.integers(0, 100, (1, 6)), jnp.int32)
        image = jnp.asarray(rng.normal(size=(1, 32, 32, 3)), jnp.float32)
        params = model.init(jax.random.PRNGKey(0), text, image)["params"]
        L = 5 + 6
        positions = jnp.arange(2, L + 2)[None, :]
        out = model.apply({"params": params}, text, image, positions=positions)
        assert out["encoder_out"].shape == (1, L, 32)

    def test_text_padding_mask(self, rng):
        model = self._model()
        text = jnp.asarray(rng.integers(0, 100, (1, 6)), jnp.int32)
        image = jnp.asarray(rng.normal(size=(1, 32, 32, 3)), jnp.float32)
        params = model.init(jax.random.PRNGKey(0), text, image)["params"]
        pad = jnp.zeros((1, 6), bool).at[0, 4:].set(True)
        out = model.apply({"params": params}, text, image, text_padding_position=pad)
        assert np.isfinite(np.asarray(out["encoder_out"])).all()


def test_vision_language_embedding_concat(rng):
    """Fused VL embedding == vision tokens then text tokens."""
    from gigapath_tpu.ops.embedding import VisionLanguageEmbedding

    class VL(nn.Module):
        @nn.compact
        def __call__(self, text, image):
            vle = VisionLanguageEmbedding(
                TextEmbedding(50, 24, name="t"),
                VisionEmbedding(32, 16, embed_dim=24, name="v"),
            )
            return vle(text, image)

    m = VL()
    text = jnp.asarray(rng.integers(0, 50, (2, 6)), jnp.int32)
    image = jnp.asarray(rng.normal(size=(2, 32, 32, 3)), jnp.float32)
    params = m.init(jax.random.PRNGKey(0), text, image)["params"]
    fused = m.apply({"params": params}, text, image)
    assert fused.shape == (2, 4 + 6, 24)
    v_only = m.apply({"params": params}, None, image)
    t_only = m.apply({"params": params}, text, None)
    np.testing.assert_allclose(np.asarray(fused[:, :4]), np.asarray(v_only), atol=1e-6)
    np.testing.assert_allclose(np.asarray(fused[:, 4:]), np.asarray(t_only), atol=1e-6)


def test_multiway_network_concat_identity(rng):
    """split at L -> all tokens through A; at 0 -> all through B."""
    make = lambda name: nn.Dense(8, name=name)  # noqa: E731
    mw = MultiwayNetwork(module_fn=make)
    x = jnp.asarray(rng.normal(size=(2, 6, 8)), jnp.float32)
    params = mw.init(jax.random.PRNGKey(0), x, split_position=3)["params"]
    full_a = mw.apply({"params": params}, x, split_position=-1)
    split_end = mw.apply({"params": params}, x, split_position=6)
    np.testing.assert_allclose(np.asarray(full_a), np.asarray(split_end), atol=1e-6)
