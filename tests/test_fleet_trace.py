"""Fleet-scoped tracing (gigapath_tpu/obs/clock.py + obs/fleet.py).

Synthetic two-process timelines pin the contracts the live dist_smoke
cannot exercise on one machine (where every process shares one
CLOCK_MONOTONIC and measured offsets are ~0): NTP offset math including
NEGATIVE offsets, lowest-RTT-wins within an epoch, reconnect
re-estimation, clock-corrected merged-timeline invariants (and their
violation detection), the exact-sum critical-path sweep, cross-process
flow arrows, and orphan semantics after a kill -9.
"""

import pytest

from gigapath_tpu.obs.clock import (
    ClockSample,
    LinkClock,
    emit_clock_sync,
    estimate_offset,
)
from gigapath_tpu.obs.fleet import FleetTimeline, ProcessDoc
from gigapath_tpu.obs.history import fold_fleet, metric_direction, new_history
from gigapath_tpu.obs.reqtrace import RequestTrace, TraceContext


# ---------------------------------------------------------------------------
# clock math
# ---------------------------------------------------------------------------

class TestClockEstimate:
    def test_symmetric_sample_recovers_true_offset(self):
        # consumer clock = producer clock + 997.0, one-way delay 0.05
        s = ClockSample(t_send=10.0, t_recv=1007.05,
                        t_reply=1007.10, t_ack=10.15)
        est = estimate_offset(s)
        assert est.offset_s == pytest.approx(997.0)
        assert est.rtt_s == pytest.approx(0.10)
        assert est.uncertainty_s == pytest.approx(0.05)
        assert est.to_reference(10.0) == pytest.approx(1007.0)

    def test_negative_offset_is_legal(self):
        # producer's monotonic origin AHEAD of the consumer's: consumer
        # clock = producer clock - 500.0 (arbitrary per-process origins)
        s = ClockSample(t_send=1000.0, t_recv=500.01,
                        t_reply=500.02, t_ack=1000.03)
        est = estimate_offset(s)
        assert est.offset_s == pytest.approx(-500.0)
        assert est.to_reference(1000.0) == pytest.approx(500.0)

    def test_rtt_clamped_nonnegative(self):
        # clock jitter can make the raw rtt formula go negative; the
        # estimate must clamp instead of reporting negative uncertainty
        s = ClockSample(t_send=0.0, t_recv=5.0, t_reply=5.2, t_ack=0.1)
        est = estimate_offset(s)
        assert est.rtt_s == 0.0
        assert est.uncertainty_s == 0.0

    def test_lowest_rtt_sample_wins_within_epoch(self):
        clk = LinkClock("chunks.w0")
        loose = ClockSample(t_send=0.0, t_recv=100.2, t_reply=100.2,
                            t_ack=0.4)     # rtt 0.4
        tight = ClockSample(t_send=1.0, t_recv=101.05, t_reply=101.05,
                            t_ack=1.1)     # rtt 0.1
        clk.update(loose)
        assert clk.uncertainty_s == pytest.approx(0.2)
        clk.update(tight)
        assert clk.uncertainty_s == pytest.approx(0.05)
        assert clk.offset_s == pytest.approx(100.0)
        # a WORSE sample never displaces the epoch's best
        clk.update(loose)
        assert clk.uncertainty_s == pytest.approx(0.05)
        assert clk.samples == 3

    def test_resync_reestimates_from_scratch(self):
        clk = LinkClock("chunks.w0")
        clk.update(ClockSample(t_send=0.0, t_recv=100.0, t_reply=100.0,
                               t_ack=0.1))
        assert clk.offset_s == pytest.approx(99.95)
        assert clk.epochs == 0
        # reconnect: the peer may be a RESTARTED process with a brand-new
        # monotonic origin — the old estimate must not survive
        clk.resync()
        assert clk.estimate is None and clk.samples == 0
        assert clk.epochs == 1
        clk.update(ClockSample(t_send=50.0, t_recv=7.0, t_reply=7.0,
                               t_ack=50.1))
        assert clk.offset_s == pytest.approx(-43.05)
        # an idle resync (no samples folded) does not burn an epoch
        clk.resync()
        clk.resync()
        assert clk.epochs == 2

    def test_emit_clock_sync_event_shape(self):
        class Log:
            def __init__(self):
                self.events = []

            def event(self, kind, **fields):
                self.events.append(dict(fields, kind=kind))

        log = Log()
        clk = LinkClock("chunks.w1")
        est = clk.update(ClockSample(t_send=0.0, t_recv=10.0,
                                     t_reply=10.0, t_ack=0.2))
        emit_clock_sync(log, clk, est)
        (ev,) = log.events
        assert ev["kind"] == "clock_sync"
        assert ev["link"] == "chunks.w1"
        assert ev["offset_s"] == pytest.approx(9.9)
        assert ev["uncertainty_s"] == pytest.approx(0.1)
        assert ev["samples"] == 1 and ev["epoch"] == 0
        # never raises with no runlog (transport paths call it blind)
        emit_clock_sync(None, clk, est)


# ---------------------------------------------------------------------------
# trace contexts: structural ids, dedup
# ---------------------------------------------------------------------------

class TestTraceContext:
    def test_structural_ids_computable_cross_process(self):
        tr = RequestTrace("tr1", 1, "slide", 0.0)
        ctx = TraceContext(tr, "w0")
        assert ctx.span_id_for("send", chunk=3) == "tr1/w0/c3/send"
        assert ctx.span_id_for("finalize") == "tr1/w0/finalize"
        # another process computes the SAME id from header fields alone
        other = TraceContext(RequestTrace("tr1", 2, "slide", 0.0),
                             "consumer")
        assert other.span_id_for("send", chunk=3).replace(
            "/consumer/", "/w0/") == ctx.span_id_for("send", chunk=3)

    def test_replay_dedups_instead_of_forking(self):
        tr = RequestTrace("tr1", 1, "slide", 0.0)
        ctx = TraceContext(tr, "consumer")
        ctx.add_span("deliver", 1.0, 1.1, chunk=0, parent="tr1/w0/c0/send")
        ctx.add_span("deliver", 2.0, 2.1, chunk=0)  # retransmit replay
        assert len(tr.spans) == 1
        sp = tr.spans[0]
        assert sp.args["span_id"] == "tr1/consumer/c0/deliver"
        assert sp.args["parent_span_id"] == "tr1/w0/c0/send"
        assert sp.args["actor"] == "consumer"


# ---------------------------------------------------------------------------
# merged timeline
# ---------------------------------------------------------------------------

TR = "tr-fleet-1"


def _sid(actor, name, chunk=None):
    if chunk is None:
        return f"{TR}/{actor}/{name}"
    return f"{TR}/{actor}/c{chunk}/{name}"


def _ev(name, t0, t1, **args):
    return {"ph": "X", "tid": 1, "name": name, "ts": t0 * 1e6,
            "dur": (t1 - t0) * 1e6, "args": args}


def _doc(actor, spans, pid=1):
    return {
        "metadata": {"clock": {"t0_monotonic": 0.0}, "actor": actor,
                     "pid": pid},
        "traceEvents": spans,
    }


def _producer_spans():
    # producer's LOCAL monotonic clock reads ~1000.x while the
    # consumer's reads ~5.x at the same instant: true offset -995.0
    return [
        _ev("dist.encode", 1000.00, 1000.02,
            span_id=_sid("w0", "dist.encode", 0), trace_id=TR, chunk=0,
            actor="w0"),
        _ev("send", 1000.02, 1000.03, span_id=_sid("w0", "send", 0),
            trace_id=TR, chunk=0, actor="w0",
            parent_span_id=_sid("w0", "dist.encode", 0)),
    ]


def _consumer_spans(fold_t0=5.04):
    return [
        _ev("deliver", 5.035, 5.04, span_id=_sid("consumer", "deliver", 0),
            trace_id=TR, chunk=0, actor="consumer",
            parent_span_id=_sid("w0", "send", 0)),
        _ev("dist.fold", fold_t0, 5.06,
            span_id=_sid("consumer", "dist.fold", 0), trace_id=TR, chunk=0,
            actor="consumer", parent_span_id=_sid("consumer", "deliver", 0)),
        _ev("dist.finalize", 5.06, 5.07,
            span_id=_sid("consumer", "dist.finalize"), trace_id=TR,
            actor="consumer"),
    ]


def _fleet(offset_s=-995.0, uncertainty_s=0.001, fold_t0=5.04):
    return FleetTimeline.from_parts([
        {"label": "w0", "doc": _doc("w0", _producer_spans(), pid=11),
         "offset_s": offset_s, "uncertainty_s": uncertainty_s},
        {"label": "consumer",
         "doc": _doc("consumer", _consumer_spans(fold_t0), pid=22),
         "offset_s": 0.0},
    ], run_id="fleet-test")


class TestFleetTimeline:
    def test_one_causal_tree_on_the_reference_axis(self):
        fleet = _fleet()
        slides = fleet.slides()
        assert list(slides) == [TR]
        assert len(slides[TR]) == 5
        send = fleet.resolve(_sid("w0", "send", 0))
        # -995.0 landed the producer span on the consumer's axis
        assert send.t1 == pytest.approx(5.03)
        deliver = fleet.resolve(_sid("consumer", "deliver", 0))
        assert fleet.resolve(deliver.parent_id) is send
        assert fleet.orphans() == []
        assert fleet.invariants() == []

    def test_wrong_offset_is_a_causality_violation(self):
        # 100ms of clock error >> uncertainty + slack: the deliver now
        # starts BEFORE its send ends on the merged axis
        fleet = _fleet(offset_s=-994.9)
        bad = fleet.invariants()
        assert len(bad) == 1 and "causality" in bad[0]
        assert "w0->consumer" in bad[0]
        # ...but error inside the measured uncertainty stays tolerated
        assert _fleet(offset_s=-995.002, uncertainty_s=0.01).invariants() \
            == []

    def test_negative_duration_and_parent_exceeding_detected(self):
        torn = _doc("consumer", [
            _ev("deliver", 5.04, 5.03, span_id=_sid("consumer", "deliver", 0),
                trace_id=TR, chunk=0, actor="consumer"),
        ])
        fleet = FleetTimeline.from_parts(
            [{"label": "consumer", "doc": torn, "offset_s": 0.0}])
        assert any("negative-duration" in v for v in fleet.invariants())
        # a fold starting well before its deliver parent
        fleet = _fleet(fold_t0=5.01)
        assert any("parent-exceeding" in v for v in fleet.invariants())

    def test_critical_path_shares_sum_to_wall_exactly(self):
        fleet = _fleet()
        row = fleet.critical_path()[TR]
        assert row["wall_s"] == pytest.approx(0.07)
        assert sum(row["seconds"].values()) == pytest.approx(row["wall_s"])
        s = row["seconds"]
        assert s["encode"] == pytest.approx(0.02)
        # wire = [send end 5.03, deliver start 5.035] on the merged axis
        assert s["wire"] == pytest.approx(0.005)
        assert s["deliver"] == pytest.approx(0.005)
        assert s["fold"] == pytest.approx(0.02)
        assert s["finalize"] == pytest.approx(0.01)
        # the send interval itself maps to no category -> idle
        assert s["idle"] == pytest.approx(0.01)
        assert row["chunks"] == 1
        assert row["straggler"] == "w0"

    def test_perfetto_flows_cross_process_only(self):
        fleet = _fleet()
        doc = fleet.perfetto()
        # ONE cross-process edge (send -> deliver); fold's parent is the
        # same-process deliver and must not draw an arrow
        assert doc["metadata"]["flows"] == 1
        starts = [e for e in doc["traceEvents"] if e.get("ph") == "s"]
        ends = [e for e in doc["traceEvents"] if e.get("ph") == "f"]
        assert len(starts) == 1 and len(ends) == 1
        assert starts[0]["pid"] != ends[0]["pid"]
        names = {e["args"]["name"] for e in doc["traceEvents"]
                 if e.get("ph") == "M" and e["name"] == "process_name"}
        assert names == {"w0", "consumer"}
        # every rebased timestamp is non-negative (fleet origin = the
        # earliest reference instant)
        assert all(e.get("ts", 0.0) >= 0.0 for e in doc["traceEvents"])

    def test_killed_producer_is_an_orphan_not_a_violation(self):
        # kill -9: the producer never ran its export closer, so only the
        # consumer doc loads; the deliver's parent ref dangles
        fleet = FleetTimeline.from_parts([
            {"label": "consumer", "doc": _doc("consumer", _consumer_spans()),
             "offset_s": 0.0},
        ], run_id="fleet-test")
        orphan_ids = {sp.span_id for sp in fleet.orphans()}
        assert orphan_ids == {_sid("consumer", "deliver", 0)}
        assert fleet.invariants() == []
        assert fleet.health()["orphans"] == 1

    def test_offset_from_last_clock_sync_after_restart(self):
        # the producer reconnected to a RESTARTED consumer: epoch 0's
        # offset is garbage for the new consumer's origin; the LAST
        # clock_sync (epoch 1, re-estimated) must win the placement
        events = [
            {"kind": "clock_sync", "link": "chunks.w0", "offset_s": 123.4,
             "uncertainty_s": 0.5, "epoch": 0, "samples": 2},
            {"kind": "clock_sync", "link": "chunks.w0", "offset_s": -995.0,
             "uncertainty_s": 0.001, "epoch": 1, "samples": 3},
        ]
        fleet = FleetTimeline.from_parts([
            {"label": "w0", "doc": _doc("w0", _producer_spans(), pid=11),
             "events": events},
            {"label": "consumer",
             "doc": _doc("consumer", _consumer_spans(), pid=22)},
        ], run_id="fleet-test")
        assert fleet.processes[0].offset_s == pytest.approx(-995.0)
        assert fleet.processes[0].uncertainty_s == pytest.approx(0.001)
        # no causality overlap after the correction
        assert fleet.invariants() == []
        clocks = fleet.health()["clocks"]
        assert clocks["chunks.w0"]["epoch"] == 1

    def test_process_without_clock_sync_is_the_reference(self):
        doc = ProcessDoc("consumer", doc=_doc("consumer", _consumer_spans()))
        assert doc.offset_s == 0.0 and doc.uncertainty_s == 0.0


# ---------------------------------------------------------------------------
# trend folding
# ---------------------------------------------------------------------------

class TestFleetTrend:
    def test_direction_rules(self):
        assert metric_direction("wire_share") == "down"
        assert metric_direction("backpressure_share") == "down"
        assert metric_direction("chunks_per_sec") == "up"
        assert metric_direction("slide_wall_s") == "down"

    def test_fold_fleet_cpu_point_is_stale_with_keys(self):
        doc = new_history()
        fold_fleet(doc, {"rc": 0, "backend": "cpu", "chunks_per_sec": 60.0,
                         "wire_share": 0.07}, "r01")
        (point,) = doc["entries"]["dist|trace"]["points"]
        assert point["stale"] is True
        assert set(point["metrics"]) == {"chunks_per_sec", "wire_share"}
