"""Disaggregated cross-stage boundary (gigapath_tpu/dist/): protocol
units, backpressure, membership/reassignment, the per-stage sharding
registry, and the ISSUE 11 acceptance — a REAL two-process CPU run that
loses a tile worker mid-slide and still produces the clean run's slide
embedding bit-exact, with the recovery on the obs bus.
"""

import glob
import json
import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gigapath_tpu.dist.boundary import (
    BoundaryConfig,
    DirChannelConsumer,
    DirChannelProducer,
    EmbeddingChunk,
    MemoryChannel,
    SlideAssembler,
    assign_chunks,
    chunk_checksum,
    plan_chunks,
)
from gigapath_tpu.dist.membership import (
    Membership,
    WorkerLease,
    reassignments_for,
    write_reassignment,
)
from gigapath_tpu.obs.runlog import RunLog


def _chunk(cid, start, stop, dim=4, slide="s0", producer="w0", seed=0):
    rng = np.random.default_rng([seed, cid])
    return EmbeddingChunk.build(
        slide, cid, start, stop,
        rng.standard_normal((stop - start, dim), dtype=np.float32),
        coords=rng.uniform(0, 100, (stop - start, 2)).astype(np.float32),
        producer=producer,
    )


def _events(path):
    with open(path, encoding="utf-8") as fh:
        return [json.loads(line) for line in fh if line.strip()]


def _run_events(root):
    """Every obs event of a (multi-process) run dir, torn tails
    tolerated — a SIGKILLed process can die mid-line."""
    events = []
    for path in glob.glob(os.path.join(str(root), "obs", "*.jsonl")):
        if os.path.basename(path).startswith("flight-"):
            continue
        with open(path, encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    events.append(json.loads(line))
                except json.JSONDecodeError:
                    continue
    return events


def _of(events, kind, **match):
    out = [ev for ev in events if ev.get("kind") == kind]
    for k, v in match.items():
        out = [ev for ev in out if ev.get(k) == v]
    return out


# ---------------------------------------------------------------------------
# chunk plan
# ---------------------------------------------------------------------------

class TestChunkPlan:
    def test_plan_covers_range_in_order(self):
        chunks = plan_chunks(50, 8)
        assert chunks[0] == (0, 0, 8)
        assert chunks[-1] == (6, 48, 50)  # ragged tail
        covered = [t for _, s, e in chunks for t in range(s, e)]
        assert covered == list(range(50))

    def test_plan_is_deterministic(self):
        assert plan_chunks(100, 16) == plan_chunks(100, 16)

    def test_plan_rejects_degenerate(self):
        with pytest.raises(ValueError):
            plan_chunks(0, 8)
        with pytest.raises(ValueError):
            plan_chunks(8, 0)

    def test_assignment_round_robin_deterministic(self):
        a = assign_chunks(range(7), ["w1", "w0"])
        # sorted workers, sorted chunks: stable however the caller orders
        assert a == {"w0": [0, 2, 4, 6], "w1": [1, 3, 5]}
        assert assign_chunks([6, 5, 4, 3, 2, 1, 0], ["w0", "w1"]) == a

    def test_reassignment_covers_exactly_the_lost_chunks(self):
        initial = assign_chunks(range(10), ["w0", "w1", "w2"])
        lost = initial["w1"]
        again = assign_chunks(lost, ["w0", "w2"])
        assert sorted(c for cs in again.values() for c in cs) == lost

    def test_assignment_requires_workers(self):
        with pytest.raises(ValueError):
            assign_chunks([0, 1], [])


# ---------------------------------------------------------------------------
# chunks + checksums
# ---------------------------------------------------------------------------

class TestChunks:
    def test_checksum_verifies_and_detects_tamper(self):
        chunk = _chunk(0, 0, 8)
        assert chunk.verify()
        chunk.payload[3, 1] += 1.0
        assert not chunk.verify()

    def test_checksum_covers_header(self):
        chunk = _chunk(2, 16, 24)
        assert chunk.checksum != chunk_checksum(
            chunk.slide_id, chunk.chunk_id, 0, 8, chunk.payload, chunk.coords
        )

    def test_build_rejects_wrong_row_count(self):
        with pytest.raises(ValueError):
            EmbeddingChunk.build("s0", 0, 0, 8,
                                 np.zeros((5, 4), np.float32))

    def test_seq_is_chunk_id(self):
        assert _chunk(7, 56, 64).seq == 7


# ---------------------------------------------------------------------------
# memory channel: credits, backpressure, dedup
# ---------------------------------------------------------------------------

class TestMemoryChannel:
    def test_producer_blocks_at_zero_credits_and_resumes_on_ack(self, tmp_path):
        """The backpressure satellite: with capacity 2, the third send
        measurably BLOCKS until the consumer acks, and the blocking
        episode lands as a schema'd ``backpressure`` event carrying
        queue depth + credits."""
        log = RunLog(str(tmp_path / "run.jsonl"), driver="t", echo=False)
        ch = MemoryChannel(BoundaryConfig(capacity=2, poll_s=0.01),
                           runlog=log, name="test")
        sent = []

        def produce():
            for cid in range(4):
                ch.send(_chunk(cid, cid * 8, cid * 8 + 8))
                sent.append(cid)

        producer = threading.Thread(target=produce)
        producer.start()
        deadline = time.monotonic() + 5
        while len(sent) < 2 and time.monotonic() < deadline:
            time.sleep(0.01)
        time.sleep(0.1)  # give the third send time to (wrongly) land
        assert sent == [0, 1], "producer must block at zero credits"

        first = ch.recv(timeout=1)
        ch.ack(first.seq)           # one credit back -> exactly one more send
        deadline = time.monotonic() + 5
        while len(sent) < 3 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert sent == [0, 1, 2], "one ack must unblock exactly one send"

        for _ in range(3):
            chunk = ch.recv(timeout=5)
            ch.ack(chunk.seq)
        producer.join(timeout=5)
        assert not producer.is_alive()
        assert ch.stats.backpressure_events >= 1
        assert ch.stats.blocked_s > 0
        log.close()
        bp = _of(_events(log.path), "backpressure", channel="test")
        assert bp, "no backpressure event on the blocking episode"
        assert bp[0]["credits"] == 0
        assert bp[0]["capacity"] == 2
        assert bp[0]["queue_depth"] >= 2

    def test_send_timeout_raises(self):
        ch = MemoryChannel(BoundaryConfig(capacity=1, poll_s=0.01))
        ch.send(_chunk(0, 0, 8))
        with pytest.raises(TimeoutError):
            ch.send(_chunk(1, 8, 16), timeout=0.05)

    def test_duplicates_deduped_by_seq(self):
        ch = MemoryChannel(BoundaryConfig(capacity=8))
        ch.send(_chunk(0, 0, 8))
        ch.ack(0)                      # free the credit, then re-send
        ch.send(_chunk(0, 0, 8))
        assert ch.recv(timeout=1).seq == 0
        assert ch.recv(timeout=0.05) is None
        assert ch.stats.duplicates == 1

    def test_corrupt_chunk_rejected(self):
        ch = MemoryChannel(BoundaryConfig(capacity=8))
        bad = _chunk(0, 0, 8)
        bad.payload[0, 0] += 1.0       # break the checksum
        ch.send(bad)
        assert ch.recv(timeout=0.05) is None
        assert ch.stats.corrupt == 1

    def test_unacked_is_the_requeue_set(self):
        ch = MemoryChannel(BoundaryConfig(capacity=8))
        for cid in range(3):
            ch.send(_chunk(cid, cid * 8, cid * 8 + 8))
        ch.ack(1)
        assert ch.unacked_seqs() == [0, 2]

    def test_digestless_chunk_is_the_intra_process_fast_path(self):
        """``build(digest=False)`` skips the sha256 (the inference
        prefetch hot path); the in-process channel trusts it, the
        cross-process consumer must NOT."""
        ch = MemoryChannel(BoundaryConfig(capacity=8))
        chunk = EmbeddingChunk.build(
            "s0", 0, 0, 8, np.zeros((8, 4), np.float32), digest=False)
        assert chunk.checksum == ""
        ch.send(chunk)
        assert ch.recv(timeout=1).seq == 0
        assert ch.stats.corrupt == 0

    def test_retrying_a_timed_out_send_is_one_backpressure_episode(
            self, tmp_path):
        """The worker's lease-renewing retry loop re-enters send for
        the SAME seq after each timeout; that is one blocking episode,
        not one event per retry."""
        log = RunLog(str(tmp_path / "run.jsonl"), driver="t", echo=False)
        ch = MemoryChannel(BoundaryConfig(capacity=1, poll_s=0.005),
                           runlog=log, name="retry")
        ch.send(_chunk(0, 0, 8))
        blocked = _chunk(1, 8, 16)
        for _ in range(3):
            with pytest.raises(TimeoutError):
                ch.send(blocked, timeout=0.02)
        assert ch.stats.backpressure_events == 1
        log.close()
        assert len(_of(_events(log.path), "backpressure")) == 1


# ---------------------------------------------------------------------------
# directory channel: cross-process protocol on one process
# ---------------------------------------------------------------------------

class TestDirChannel:
    def test_roundtrip_out_of_order_and_ack_credits(self, tmp_path):
        root = str(tmp_path)
        cfg = BoundaryConfig(capacity=8, poll_s=0.005)
        prod = DirChannelProducer(root, cfg, producer="w0")
        cons = DirChannelConsumer(root, cfg)
        for cid in (2, 0, 1):          # out of order on purpose
            prod.send(_chunk(cid, cid * 8, cid * 8 + 8))
        assert prod.credits() == 5
        got = {}
        for _ in range(3):
            chunk = cons.recv(timeout=2)
            assert chunk is not None and chunk.verify()
            cons.ack(chunk.seq)
            got[chunk.seq] = chunk
        assert sorted(got) == [0, 1, 2]
        assert prod.credits() == 8     # acks refunded every credit
        assert prod.unacked_seqs() == []

    def test_retransmit_heals_a_dropped_write(self, tmp_path):
        from gigapath_tpu.resilience.chaos import ChaosInjector

        root = str(tmp_path)
        cfg = BoundaryConfig(capacity=8, poll_s=0.005, retransmit_s=0.05)
        chaos = ChaosInjector("drop_chunk@0")
        prod = DirChannelProducer(root, cfg, producer="w0", chaos=chaos)
        cons = DirChannelConsumer(root, cfg)
        prod.send(_chunk(0, 0, 8))
        assert prod.stats.dropped == 1
        assert cons.recv(timeout=0.1) is None, "the drop must actually drop"
        time.sleep(0.06)
        assert prod.pump_retransmits() == 1
        chunk = cons.recv(timeout=2)
        assert chunk is not None and chunk.seq == 0
        assert prod.stats.retransmits == 1

    def test_dup_chunk_deduped(self, tmp_path):
        from gigapath_tpu.resilience.chaos import ChaosInjector

        root = str(tmp_path)
        cfg = BoundaryConfig(capacity=8, poll_s=0.005)
        chaos = ChaosInjector("dup_chunk@1")
        prod = DirChannelProducer(root, cfg, producer="w0", chaos=chaos)
        cons = DirChannelConsumer(root, cfg)
        prod.send(_chunk(1, 8, 16))
        first = cons.recv(timeout=2)
        assert first is not None and first.seq == 1
        assert cons.recv(timeout=0.1) is None
        assert cons.stats.duplicates == 1

    def test_dir_consumer_rejects_digestless_chunks(self, tmp_path):
        """Cross-process transports must digest: an empty checksum is
        treated as corrupt, never assembled."""
        root = str(tmp_path)
        cfg = BoundaryConfig(capacity=8, poll_s=0.005)
        prod = DirChannelProducer(root, cfg, producer="w0")
        cons = DirChannelConsumer(root, cfg)
        prod.send(EmbeddingChunk.build(
            "s0", 0, 0, 8, np.zeros((8, 4), np.float32), digest=False))
        assert cons.recv(timeout=0.1) is None
        assert cons.stats.corrupt == 1

    def test_seeded_watermark_retransmit_is_reacked(self, tmp_path):
        """A restarted consumer's watermark may cover a seq whose
        deferred ack died with the predecessor (crash between
        checkpoint commit and ack flush): the retransmit must be
        swallowed AND re-acked, or the producer's credit is pinned
        forever."""
        root = str(tmp_path)
        cfg = BoundaryConfig(capacity=2, poll_s=0.005, retransmit_s=0.05)
        prod = DirChannelProducer(root, cfg, producer="w0")
        prod.send(_chunk(0, 0, 8))
        # the predecessor consumer delivered + checkpointed seq 0 but
        # died before the ack flush; the restart seeds the watermark
        cons = DirChannelConsumer(root, cfg, delivered=[0])
        time.sleep(0.06)
        assert prod.pump_retransmits() == 1
        assert cons.recv(timeout=0.1) is None     # deduped, not re-folded
        assert cons.stats.duplicates >= 1
        assert prod.credits() == 2, "the swallowed retransmit must re-ack"
        assert prod.unacked_seqs() == []

    def test_deferred_ack_duplicate_is_not_reacked(self, tmp_path):
        """The inverse guard: a retransmit duplicate of a chunk whose
        ack is still DEFERRED (delivered this session, not yet covered
        by a checkpoint) must NOT be acked — an ack is a durability
        promise, and acking here would let a crash strand the chunk
        forever (found by end-to-end verification: the predecessor
        consumer deduped a retransmit of an uncheckpointed chunk, acked
        it, died, and the slide could never complete)."""
        root = str(tmp_path)
        cfg = BoundaryConfig(capacity=2, poll_s=0.005, retransmit_s=0.05)
        prod = DirChannelProducer(root, cfg, producer="w0")
        cons = DirChannelConsumer(root, cfg)
        prod.send(_chunk(0, 0, 8))
        assert cons.recv(timeout=1).seq == 0   # delivered, ack DEFERRED
        time.sleep(0.06)
        assert prod.pump_retransmits() == 1
        assert cons.recv(timeout=0.1) is None  # deduped
        assert cons.stats.duplicates >= 1
        assert prod.credits() == 1, (
            "a deferred-ack duplicate must not refund the credit"
        )
        cons.ack(0)                            # the checkpoint commits
        assert prod.credits() == 2

    def test_backpressure_event_from_dir_producer(self, tmp_path):
        log = RunLog(str(tmp_path / "run.jsonl"), driver="t", echo=False)
        cfg = BoundaryConfig(capacity=1, poll_s=0.005)
        prod = DirChannelProducer(str(tmp_path), cfg, producer="w0",
                                  runlog=log)
        prod.send(_chunk(0, 0, 8))
        with pytest.raises(TimeoutError):
            prod.send(_chunk(1, 8, 16), timeout=0.05)
        log.close()
        bp = _of(_events(log.path), "backpressure")
        assert bp and bp[0]["credits"] == 0 and bp[0]["capacity"] == 1


# ---------------------------------------------------------------------------
# assembly
# ---------------------------------------------------------------------------

class TestAssembler:
    def test_out_of_order_assembly_is_exact(self):
        chunks = [_chunk(cid, cid * 8, cid * 8 + 8, dim=4)
                  for cid in range(4)]
        direct = np.concatenate([c.payload for c in chunks])
        asm = SlideAssembler(32, 4)
        asm.expect(range(4))
        for c in (chunks[3], chunks[0], chunks[2], chunks[1]):
            assert asm.add(c)
        assert asm.complete()
        np.testing.assert_array_equal(asm.embeds, direct)

    def test_duplicate_add_ignored_and_missing_tracked(self):
        asm = SlideAssembler(16, 4)
        asm.expect([0, 1])
        c = _chunk(0, 0, 8)
        assert asm.add(c)
        assert not asm.add(c)
        assert asm.missing() == [1]
        assert not asm.complete()


# ---------------------------------------------------------------------------
# membership + reassignment
# ---------------------------------------------------------------------------

class TestMembership:
    def test_renew_keeps_alive_expiry_reports_once(self, tmp_path):
        root = str(tmp_path)
        log = RunLog(os.path.join(root, "run.jsonl"), driver="t", echo=False)
        lease = WorkerLease(root, "w0", lease_s=10.0)
        lease.register(now=100.0)
        m = Membership(root, runlog=log)
        assert m.alive(now=105.0) == ["w0"]
        assert m.poll_lost(now=105.0) == []
        # renew pushes expiry out
        lease.renew(now=109.0)
        assert m.alive(now=115.0) == ["w0"]
        # silence past expiry -> lost, exactly once
        assert m.poll_lost(now=130.0) == ["w0"]
        assert m.poll_lost(now=131.0) == []
        assert m.lost() == ["w0"]
        log.close()
        lost = _of(_events(log.path), "worker_lost", worker="w0")
        assert len(lost) == 1
        assert lost[0]["stage"] == "tile"
        assert lost[0]["expired_by_s"] > 0

    def test_renew_is_rate_limited(self, tmp_path):
        lease = WorkerLease(str(tmp_path), "w0", lease_s=9.0)
        lease.register(now=100.0)
        assert not lease.renew(now=101.0)   # < lease/3 elapsed
        assert lease.renew(now=103.1)

    def test_retire_removes_the_lease(self, tmp_path):
        root = str(tmp_path)
        lease = WorkerLease(root, "w0", lease_s=10.0)
        lease.register(now=100.0)
        lease.retire()
        assert Membership(root).alive(now=100.1) == []

    def test_reassignment_roundtrip_and_recovery_event(self, tmp_path):
        root = str(tmp_path)
        log = RunLog(os.path.join(root, "run.jsonl"), driver="t", echo=False)
        write_reassignment(root, lost_worker="w0",
                           assignments={"w1": [4, 2], "w2": [6]},
                           runlog=log)
        seen: set = set()
        assert reassignments_for(root, "w1", seen) == [2, 4]
        assert reassignments_for(root, "w1", seen) == []  # once per file
        assert reassignments_for(root, "w2") == [6]
        log.close()
        rec = _of(_events(log.path), "recovery", action="reassign")
        assert rec and rec[0]["worker"] == "w0" and rec[0]["chunks"] == 3
        assert rec[0]["survivors"] == ["w1", "w2"]

    def test_report_lost_is_direct_evidence_once(self, tmp_path):
        """The orchestrator's process-exit probe marks a worker lost
        without any lease (startup deaths have none); once per worker,
        and the lease path never double-reports it."""
        root = str(tmp_path)
        log = RunLog(os.path.join(root, "run.jsonl"), driver="t", echo=False)
        m = Membership(root, runlog=log)
        assert m.report_lost("w9", reason="process_exit", exit_code=-9)
        assert not m.report_lost("w9", reason="process_exit", exit_code=-9)
        assert m.lost() == ["w9"]
        log.close()
        lost = _of(_events(log.path), "worker_lost", worker="w9")
        assert len(lost) == 1 and lost[0]["reason"] == "process_exit"

    def test_crashed_worker_leaves_its_lease_clean_exit_retires(
            self, tmp_path):
        """A worker that does NOT exit cleanly must leave its lease to
        expire (that is how a lease-only coordinator learns of the
        death); a clean exit retires it."""
        from gigapath_tpu.dist.worker import run_tile_worker, write_plan
        from gigapath_tpu.dist.pipeline import default_plan

        root = str(tmp_path)
        plan = default_plan(n_tiles=8, chunk_tiles=8, lease_s=30.0,
                            workers=["w0"])
        write_plan(root, plan)
        # deadline 0: the loop never runs, status='deadline' (not ok)
        run_tile_worker(root, "w0", deadline_s=0.0)
        assert Membership(root).alive() == ["w0"], (
            "a non-clean exit must NOT retire the lease"
        )
        # clean exit: DONE pre-published, worker drains and retires
        from gigapath_tpu.dist.worker import DONE_MARKER

        with open(os.path.join(root, DONE_MARKER), "w"):
            pass
        run_tile_worker(root, "w0", deadline_s=30.0)
        assert Membership(root).alive() == []

    def test_credit_blocked_worker_drains_on_done(self, tmp_path):
        """A worker stuck at zero credits (nobody acking) must drain
        out the moment DONE is published — not spin to its own
        deadline."""
        from gigapath_tpu.dist.pipeline import default_plan
        from gigapath_tpu.dist.worker import (
            DONE_MARKER,
            run_tile_worker,
            write_plan,
        )

        root = str(tmp_path)
        plan = default_plan(n_tiles=16, chunk_tiles=8, lease_s=0.4,
                            credits=1, workers=["w0"])
        write_plan(root, plan)
        with open(os.path.join(root, DONE_MARKER), "w"):
            pass
        t0 = time.monotonic()
        stats = run_tile_worker(root, "w0", deadline_s=30.0)
        wall = time.monotonic() - t0
        assert stats["status"] == "ok"       # orderly drain, not failure
        assert stats["sent"] == 1            # second chunk never acked
        assert wall < 5, f"drain took {wall:.1f}s — spun past DONE"

    def test_anomaly_engine_reacts_to_worker_lost(self, tmp_path):
        from gigapath_tpu.obs.anomaly import AnomalyConfig, attach_anomaly_engine

        log = RunLog(str(tmp_path / "run.jsonl"), driver="t", echo=False)
        engine = attach_anomaly_engine(
            log, config=AnomalyConfig(capture_budget=0))
        log.event("worker_lost", worker="w3", stage="tile",
                  expired_by_s=0.5)
        log.close()
        fired = [a for a in engine.anomalies
                 if a.get("detector") == "worker_lost"]
        assert fired and fired[0]["worker"] == "w3"
        assert fired[0]["flight"], "worker_lost must dump flight context"


# ---------------------------------------------------------------------------
# chaos parsing
# ---------------------------------------------------------------------------

class TestDistChaos:
    def test_new_injectors_parse(self):
        from gigapath_tpu.resilience.chaos import ChaosInjector

        c = ChaosInjector("kill_worker@3,slow_worker@2:0.5,drop_chunk@1,"
                          "dup_chunk@4")
        assert c._kill_worker_after == 3
        assert c.slow_worker(2) == 0.5 and c.slow_worker(0) == 0.0
        assert c.drops_chunk(1) and not c.drops_chunk(1)  # one-shot
        assert c.dups_chunk(4) and not c.dups_chunk(4)

    def test_slow_worker_star_slows_every_chunk(self):
        from gigapath_tpu.resilience.chaos import ChaosInjector

        c = ChaosInjector("slow_worker@*:0.2")
        assert c.slow_worker(0) == 0.2 and c.slow_worker(99) == 0.2

    def test_null_chaos_has_the_surface(self):
        from gigapath_tpu.resilience.chaos import NullChaos

        n = NullChaos()
        assert not n.maybe_kill_worker(5)
        assert n.slow_worker(0) == 0.0
        assert not n.drops_chunk(0) and not n.dups_chunk(0)

    def test_unknown_injector_still_raises(self):
        from gigapath_tpu.resilience.chaos import ChaosInjector

        with pytest.raises(ValueError):
            ChaosInjector("explode_worker@1")


# ---------------------------------------------------------------------------
# stage meshes + the sharding-rule registry
# ---------------------------------------------------------------------------

class TestStageMesh:
    def test_match_partition_rules_first_match_wins(self):
        from jax.sharding import PartitionSpec as P

        from gigapath_tpu.dist.stagemesh import match_partition_rules

        params = {
            "layer": {"fc1": {"kernel": np.zeros((4, 8))},
                      "fc2": {"kernel": np.zeros((8, 4)),
                              "bias": np.zeros((4,))}},
            "scale": np.ones(()),
        }
        specs = match_partition_rules(
            (
                (r"fc1/kernel$", P(None, "model")),
                (r"fc2/kernel$", P("model", None)),
                (r".*", P()),
            ),
            params,
        )
        assert specs["layer"]["fc1"]["kernel"] == P(None, "model")
        assert specs["layer"]["fc2"]["kernel"] == P("model", None)
        assert specs["layer"]["fc2"]["bias"] == P()
        # scalars never partition, whatever the rules say
        assert specs["scale"] == P()

    def test_uncovered_param_is_a_loud_error(self):
        from jax.sharding import PartitionSpec as P

        from gigapath_tpu.dist.stagemesh import match_partition_rules

        with pytest.raises(ValueError, match="no partition rule"):
            match_partition_rules(
                ((r"fc1/kernel$", P()),),
                {"other": {"kernel": np.zeros((4, 4))}},
            )

    def test_registry_has_both_stages(self):
        from gigapath_tpu.dist.stagemesh import get_stage, stage_names

        assert stage_names() == ["slide_encoder", "tile_encoder"]
        assert get_stage("tile_encoder").axes == ("data", "model")
        assert get_stage("slide_encoder").axes == ("data", "seq", "model")
        with pytest.raises(KeyError):
            get_stage("nope")

    def test_stage_mesh_axes_and_device_subset(self):
        from gigapath_tpu.dist.stagemesh import stage_mesh

        devices = jax.devices()
        tile = stage_mesh("tile_encoder", devices=devices[:4])
        assert tile.axis_names == ("data", "model")
        assert tile.devices.size == 4
        slide = stage_mesh("slide_encoder", devices=devices[4:])
        assert slide.axis_names == ("data", "seq", "model")
        assert {d.id for d in tile.devices.flat}.isdisjoint(
            {d.id for d in slide.devices.flat}
        )

    def test_stage_param_shardings_cover_a_real_model(self):
        from gigapath_tpu.dist.stagemesh import (
            stage_mesh,
            stage_param_shardings,
        )
        from gigapath_tpu.models.classification_head import get_model

        _, params = get_model(
            input_dim=16, latent_dim=32, feat_layer="1", n_classes=2,
            model_arch="gigapath_slide_enc_tiny", dtype=None,
        )
        mesh = stage_mesh("slide_encoder", devices=jax.devices()[:8],
                          axis_sizes={"data": 1, "seq": 4, "model": 2})
        shardings = stage_param_shardings("slide_encoder", params, mesh)
        leaves = jax.tree_util.tree_leaves(
            shardings, is_leaf=lambda x: hasattr(x, "spec"))
        assert leaves and all(hasattr(s, "spec") for s in leaves)
        # at least one kernel actually tensor-parallel under the rules
        flat = jax.tree_util.tree_flatten_with_path(params)[0]
        specs = jax.tree_util.tree_flatten_with_path(
            jax.tree_util.tree_map(lambda s: s, shardings,
                                   is_leaf=lambda x: hasattr(x, "spec")))[0]
        split = [s for (_, s) in specs if any(e is not None for e in s.spec)]
        assert split, "no parameter picked up a model-parallel rule"

    def test_degrade_drops_missing_axes(self):
        from gigapath_tpu.dist.stagemesh import (
            stage_mesh,
            stage_param_shardings,
        )

        params = {"fc1": {"kernel": np.zeros((4, 8), np.float32)}}
        mesh = stage_mesh("tile_encoder", devices=jax.devices()[:1])
        shardings = stage_param_shardings("tile_encoder", params, mesh)
        # a 1-device mesh has no live axes: everything degrades to P()
        assert all(not any(e is not None for e in s.spec)
                   for s in jax.tree_util.tree_leaves(
                       shardings, is_leaf=lambda x: hasattr(x, "spec")))


# ---------------------------------------------------------------------------
# zero retraces: channel on vs off
# ---------------------------------------------------------------------------

class TestChannelRetraceParity:
    def test_channel_fed_forward_compiles_once(self):
        """The boundary moves numpy on the host; feeding a jitted
        forward through it must hit the SAME jit cache entry as feeding
        it directly — zero extra compiles with the channel on."""

        @jax.jit
        def forward(x):
            return jnp.tanh(x).sum(axis=0)

        chunks = [_chunk(cid, cid * 8, cid * 8 + 8, dim=4)
                  for cid in range(4)]
        direct = np.concatenate([c.payload for c in chunks])
        out_direct = np.asarray(forward(direct))
        assert forward._cache_size() == 1

        ch = MemoryChannel(BoundaryConfig(capacity=8))
        for c in chunks:
            ch.send(c)
        asm = SlideAssembler(32, 4)
        asm.expect(range(4))
        while not asm.complete():
            chunk = ch.recv(timeout=1)
            asm.add(chunk)
            ch.ack(chunk.seq)
        out_channel = np.asarray(forward(asm.embeds))
        assert forward._cache_size() == 1, "the channel caused a retrace"
        np.testing.assert_array_equal(out_direct, out_channel)


# ---------------------------------------------------------------------------
# inference prefetch wiring
# ---------------------------------------------------------------------------

class TestInferencePrefetch:
    def _fixture(self, tmp_path, n=5):
        from gigapath_tpu.utils.checkpoint import save_checkpoint

        rng = np.random.default_rng(0)
        feature_dir = tmp_path / "features"
        for i in range(n):
            save_checkpoint(
                str(feature_dir / f"s{i}_features"),
                {"features": rng.normal(size=(8 + i, 16)).astype(np.float32),
                 "coords": rng.uniform(0, 100, (8 + i, 2)).astype(np.float32)},
            )
        return str(feature_dir)

    def test_stream_matches_synchronous_loads(self, tmp_path):
        from gigapath_tpu.inference import _feature_stream, _load_features

        feature_dir = self._fixture(tmp_path)
        files = sorted(glob.glob(os.path.join(feature_dir, "*_features.pt")))
        if not files:  # orbax feature dirs, not .pt files
            files = sorted(
                os.path.join(feature_dir, d)
                for d in os.listdir(feature_dir)
            )
        plain = [(i, p, *_load_features(p)) for i, p in enumerate(files)]
        streamed = list(_feature_stream(files, prefetch=2, runlog=None))
        assert [s[0] for s in streamed] == [p[0] for p in plain]
        for (pi, pp, pf, pc), (si, sp, sf, sc) in zip(plain, streamed):
            assert pp == sp
            np.testing.assert_array_equal(
                np.asarray(pf, np.float32), sf)
            np.testing.assert_array_equal(
                np.asarray(pc, np.float32), sc)

    def test_loader_failure_propagates(self, tmp_path):
        from gigapath_tpu.inference import _feature_stream

        with pytest.raises(Exception):
            list(_feature_stream(
                [str(tmp_path / "missing_features.pt")], prefetch=2,
                runlog=None,
            ))


# ---------------------------------------------------------------------------
# THE acceptance: two process groups, one killed mid-slide, bit-exact
# ---------------------------------------------------------------------------

class TestKillWorkerAcceptance:
    def test_kill_worker_recovery_is_bit_exact(self, tmp_path):
        """ISSUE 11 acceptance: a real two-process CPU run loses a tile
        worker mid-slide (SIGKILL via ``kill_worker@1``); the survivors
        reassign the lost tile range and the final slide embedding is
        bit-exact vs the uninterrupted run, with ``worker_lost`` +
        ``recovery action="reassign"`` on the bus and zero unexpected
        retraces."""
        from gigapath_tpu.dist.pipeline import default_plan, run_disaggregated

        # lease 1.5s: workers renew every 0.5s, so only a genuinely dead
        # worker expires, even on a loaded CI box; recovery latency in
        # the chaos half is bounded by this same window
        plan = default_plan(n_tiles=40, chunk_tiles=8, lease_s=1.5,
                            credits=4, retransmit_s=0.5)
        clean = run_disaggregated(str(tmp_path / "clean"), plan=plan,
                                  deadline_s=90)
        assert clean["lost"] == [] and clean["reassignments"] == 0
        assert all(rc == 0 for rc in clean["worker_exit_codes"].values())

        chaos = run_disaggregated(
            str(tmp_path / "chaos"), plan=plan,
            worker_chaos={"w0": "kill_worker@1"}, deadline_s=90,
        )
        assert chaos["worker_exit_codes"]["w0"] == -9, (
            f"w0 survived: {chaos['worker_exit_codes']}"
        )
        assert chaos["lost"] == ["w0"]
        assert chaos["reassignments"] >= 1

        # bit-parity: the assembled sequence AND the slide embedding
        np.testing.assert_array_equal(clean["assembled"],
                                      chaos["assembled"])
        np.testing.assert_array_equal(clean["embedding"],
                                      chaos["embedding"])

        events = _run_events(tmp_path / "chaos")
        assert _of(events, "worker_lost", worker="w0")
        reassigns = _of(events, "recovery", action="reassign")
        assert reassigns and reassigns[0]["worker"] == "w0"
        assert reassigns[0]["chunks"] >= 1
        assert _of(events, "anomaly", detector="worker_lost")
        unexpected = [ev for ev in _of(events, "compile")
                      if ev.get("unexpected")]
        assert not unexpected, unexpected


# ---------------------------------------------------------------------------
# ISSUE 13 acceptance (a): the TCP transport under frame chaos
# ---------------------------------------------------------------------------

class TestTcpBoundaryAcceptance:
    def test_tcp_chaos_run_is_bit_exact_vs_memory_channel_oracle(
            self, tmp_path):
        """ISSUE 13 acceptance (a): a REAL two-process run joined by
        the TCP transport, under ``drop_conn`` (torn frame + dead
        connection) and ``corrupt_frame`` (flipped body bytes) chaos,
        produces a slide embedding BIT-exact vs a clean in-process
        MemoryChannel oracle — with the frame errors counted, a
        ``reconnect`` recovery event on the bus, and zero unexpected
        retraces."""
        from gigapath_tpu.dist.boundary import (
            BoundaryConfig,
            MemoryChannel,
            SlideAssembler,
        )
        from gigapath_tpu.dist.pipeline import (
            _default_forward,
            default_plan,
            run_disaggregated,
        )
        from gigapath_tpu.dist.worker import encode_chunk, encoder_weights

        plan = default_plan(n_tiles=40, chunk_tiles=8, lease_s=1.5,
                            credits=4, retransmit_s=0.5, transport="tcp")

        # the clean MemoryChannel oracle: same chunks, in process,
        # through the third transport of the same protocol
        weights = encoder_weights(plan)
        channel = MemoryChannel(BoundaryConfig(capacity=8))
        chunks = plan_chunks(plan["n_tiles"], plan["chunk_tiles"])
        for cid, start, stop in chunks:
            embeds, coords = encode_chunk(plan, weights, start, stop)
            channel.send(EmbeddingChunk.build(
                plan["slide_id"], cid, start, stop, embeds, coords=coords,
            ))
        asm = SlideAssembler(plan["n_tiles"], plan["dim_out"])
        asm.expect([c[0] for c in chunks])
        while not asm.complete():
            chunk = channel.recv(timeout=1)
            asm.add(chunk)
            channel.ack(chunk.seq)
        forward, params = _default_forward()(plan["dim_out"])
        oracle = np.asarray(
            forward(params, asm.embeds[None], asm.coords[None]), np.float32
        )[0]

        chaos = run_disaggregated(
            str(tmp_path / "tcp-chaos"), plan=plan,
            worker_chaos={"w0": "drop_conn@1,corrupt_frame@2"},
            deadline_s=90,
        )
        np.testing.assert_array_equal(chaos["embedding"], oracle)
        np.testing.assert_array_equal(chaos["assembled"], asm.embeds)
        assert chaos["stats"]["frame_errors"] >= 1, chaos["stats"]
        assert chaos["lost"] == [], "frame chaos must not read as death"

        events = _run_events(tmp_path / "tcp-chaos")
        assert _of(events, "recovery", action="reconnect"), (
            "drop_conn must surface as a reconnect recovery event"
        )
        unexpected = [ev for ev in _of(events, "compile")
                      if ev.get("unexpected")]
        assert not unexpected, unexpected


# ---------------------------------------------------------------------------
# ISSUE 13 acceptance (b): consumer SIGKILL + checkpoint resume
# ---------------------------------------------------------------------------

class TestConsumerKillAcceptance:
    def test_consumer_sigkill_resumes_from_watermark_bit_exact(
            self, tmp_path):
        """ISSUE 13 acceptance (b): the slide consumer (own OS process,
        streaming fold, TCP transport, checkpoint cadence 2) is
        SIGKILLed mid-slide; the restarted consumer finds the
        checkpoint, reloads the watermark, re-handshakes, receives only
        post-watermark chunks, and produces a BIT-exact embedding — with
        ``consumer_lost`` + ``recovery action="consumer_resume"`` on the
        bus and zero unexpected retraces."""
        from gigapath_tpu.dist.pipeline import default_plan, run_disaggregated

        plan = default_plan(n_tiles=40, chunk_tiles=8, lease_s=2.0,
                            credits=4, retransmit_s=0.5,
                            chunked_prefill=True, transport="tcp",
                            consumer_ckpt_every=2)
        clean = run_disaggregated(str(tmp_path / "clean"), plan=plan,
                                  deadline_s=90)
        assert clean["streaming"]

        chaos = run_disaggregated(
            str(tmp_path / "kill"), plan=plan,
            consumer_chaos="kill_consumer@3", deadline_s=90,
        )
        exits = chaos["consumer_exit_codes"]
        assert exits[0] == -9, f"consumer was not SIGKILLed: {exits}"
        assert exits[-1] == 0, f"restarted consumer failed: {exits}"
        np.testing.assert_array_equal(clean["embedding"],
                                      chaos["embedding"])

        events = _run_events(tmp_path / "kill")
        lost = _of(events, "consumer_lost")
        assert lost and lost[0].get("reason") == "checkpoint_found"
        resumes = _of(events, "recovery", action="consumer_resume")
        assert resumes and resumes[0].get("chunks", 0) >= 1, resumes
        assert _of(events, "anomaly", detector="consumer_lost"), (
            "the anomaly engine did not react to consumer_lost"
        )
        unexpected = [ev for ev in _of(events, "compile")
                      if ev.get("unexpected")]
        assert not unexpected, unexpected


# ---------------------------------------------------------------------------
# ISSUE 12: the consumer folds chunks on arrival (streaming prefill)
# ---------------------------------------------------------------------------

class TestStreamingConsumer:
    def test_streaming_fold_on_arrival_kill_recover_bit_exact(self, tmp_path):
        """ISSUE 12 acceptance (dist leg): with ``chunked_prefill`` in
        the plan the consumer folds every acked ``EmbeddingChunk``
        straight into the streaming slide-encoder session — no dense
        ``[n_tiles, D]`` assembly — and a SIGKILLed worker's
        reassignment (out-of-order, retransmitted delivery included)
        leaves the slide embedding BIT-exact vs the clean streaming run,
        which itself matches the dense consumer at streaming tolerance."""
        from gigapath_tpu.dist.pipeline import default_plan, run_disaggregated

        plan = default_plan(n_tiles=40, chunk_tiles=8, lease_s=1.5,
                            credits=4, retransmit_s=0.5)
        dense = run_disaggregated(str(tmp_path / "dense"), plan=plan,
                                  deadline_s=90)

        stream_plan = dict(plan, chunked_prefill=True)
        clean = run_disaggregated(str(tmp_path / "clean"), plan=stream_plan,
                                  deadline_s=90)
        assert clean["streaming"] and clean["assembled"] is None
        assert clean["lost"] == [] and clean["reassignments"] == 0
        np.testing.assert_allclose(clean["embedding"], dense["embedding"],
                                   atol=1e-5, rtol=0)

        chaos = run_disaggregated(
            str(tmp_path / "chaos"), plan=stream_plan,
            worker_chaos={"w0": "kill_worker@1"}, deadline_s=90,
        )
        assert chaos["worker_exit_codes"]["w0"] == -9
        assert chaos["lost"] == ["w0"] and chaos["reassignments"] >= 1
        np.testing.assert_array_equal(clean["embedding"],
                                      chaos["embedding"])

        events = _run_events(tmp_path / "clean")
        assert _of(events, "stream_open")
        assert _of(events, "stream_finalize")
