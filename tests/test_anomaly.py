"""Anomaly engine, flight recorder, triggered profiler capture, history.

The load-bearing contracts from ISSUE 6's acceptance criteria:

- every detector flips BOTH ways on synthetic step streams (fires on
  the seeded anomaly, stays quiet on the healthy twin);
- a forced error / anomaly dumps the flight recorder with the buffered
  context, and the dump budget bounds a flapping trigger;
- the profiler-capture budget bounds trace captures, and captures stop
  after K steps;
- a forced stall in a real CPU driver run (inference) produces an
  ``anomaly`` event, a flight dump and a profiler trace dir — while the
  obs-off twin produces none of the three and compiles exactly as
  often;
- obs off / anomaly off leaves NOTHING on disk and adds zero retraces.
"""

import glob
import json
import logging
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gigapath_tpu.obs import (
    AnomalyConfig,
    AnomalyEngine,
    NullAnomalyEngine,
    NullRunLog,
    RunLog,
    attach_anomaly_engine,
    get_run_log,
)
from gigapath_tpu.obs.watchdog import CompileWatchdog

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO_ROOT, "scripts"))


def read_events(path):
    with open(path) as fh:
        return [json.loads(line) for line in fh if line.strip()]


def anomaly_events(path, detector=None):
    out = [ev for ev in read_events(path) if ev["kind"] == "anomaly"]
    if detector is not None:
        out = [ev for ev in out if ev.get("detector") == detector]
    return out


def make_engine(tmp_path, **cfg_overrides):
    """RunLog + engine with test-friendly thresholds; profiler capture
    off unless the test opts in."""
    cfg = AnomalyConfig(capture_budget=0, warmup_steps=4, cooldown_steps=4)
    for k, v in cfg_overrides.items():
        setattr(cfg, k, v)
    log = RunLog(str(tmp_path / "run.jsonl"), driver="t", echo=False)
    engine = attach_anomaly_engine(log, config=cfg)
    return log, engine


# ---------------------------------------------------------------------------
# detectors: each one flips both ways on a synthetic step stream
# ---------------------------------------------------------------------------

class TestDetectors:
    def test_step_time_spike_fires_and_steady_stream_does_not(self, tmp_path):
        log, engine = make_engine(tmp_path)
        for i in range(10):
            log.step(i, wall_s=0.01, synced=True)
        assert anomaly_events(log.path) == []  # healthy twin: quiet
        log.step(10, wall_s=0.2, synced=True)  # 20x the EWMA
        (ev,) = anomaly_events(log.path, "step_time_spike")
        assert ev["step"] == 10
        assert ev["value"] == 0.2
        assert ev["baseline"] == pytest.approx(0.01, rel=0.1)
        assert ev["flight"]  # the reaction fired too
        log.close()

    def test_spike_needs_warmup(self, tmp_path):
        log, _ = make_engine(tmp_path, warmup_steps=8)
        log.step(0, wall_s=0.01, synced=True)
        log.step(1, wall_s=5.0, synced=True)  # huge, but unbaselined
        assert anomaly_events(log.path, "step_time_spike") == []
        log.close()

    def test_unsynced_walls_never_spike(self, tmp_path):
        """Unsynced wall_s is dispatch time under async dispatch —
        spiking on it would be pure noise."""
        log, _ = make_engine(tmp_path)
        for i in range(10):
            log.step(i, wall_s=0.01, synced=True)
        log.step(10, wall_s=0.9, synced=False)
        assert anomaly_events(log.path) == []
        log.close()

    def test_compile_paying_step_is_exempt_and_kept_out_of_baseline(
        self, tmp_path
    ):
        """A new bucket's first synced step legitimately carries minutes
        of XLA compile wall — not a spike, and not baseline input."""
        log, _ = make_engine(tmp_path)
        for i in range(10):
            log.step(i, wall_s=0.01, synced=True)
        log.compile_event("step", (1, 256), 4.0, count=1)
        log.step(10, wall_s=4.0, synced=True)  # the compile-paying step
        assert anomaly_events(log.path) == []
        # ... and it did not poison the EWMA: a real spike still fires
        # against the 0.01 baseline
        log.step(11, wall_s=0.01, synced=True)
        log.step(12, wall_s=0.3, synced=True)
        (ev,) = anomaly_events(log.path, "step_time_spike")
        assert ev["baseline"] == pytest.approx(0.01, rel=0.1)
        log.close()

    def test_spike_baselines_are_bucket_keyed(self, tmp_path):
        """Bucketed training runs order-of-magnitude different walls per
        bucket — crossing buckets must not read as a spike, but a spike
        WITHIN a bucket must."""
        log, _ = make_engine(tmp_path)
        for i in range(8):  # interleaved buckets, 8 samples each
            log.step(2 * i, wall_s=0.01, synced=True, bucket="(1, 128)")
            log.step(2 * i + 1, wall_s=0.5, synced=True, bucket="(1, 4096)")
        assert anomaly_events(log.path) == []  # 50x across buckets: fine
        log.step(16, wall_s=0.2, synced=True, bucket="(1, 128)")
        (ev,) = anomaly_events(log.path, "step_time_spike")
        assert ev["bucket"] == "(1, 128)"
        assert ev["baseline"] == pytest.approx(0.01, rel=0.1)
        log.close()

    def test_cooldown_bounds_anomalies_per_bad_regime(self, tmp_path):
        log, _ = make_engine(tmp_path, cooldown_steps=100)
        for i in range(10):
            log.step(i, wall_s=0.01, synced=True)
        for i in range(10, 16):
            log.step(i, wall_s=0.5, synced=True)  # persistently bad
        assert len(anomaly_events(log.path, "step_time_spike")) == 1
        log.close()

    def test_throughput_dip_fires_and_recovers(self, tmp_path):
        """Fed directly with records carrying controlled arrival times
        (runlog.event stamps real wall clocks — useless for this)."""
        log, engine = make_engine(tmp_path, dip_factor=3.0)
        t = 1000.0
        for i in range(10):  # steady 10 steps/s baseline
            engine.on_event({"kind": "step", "step": i, "t": t})
            t += 0.1
        assert anomaly_events(log.path) == []
        for i in range(10, 20):  # collapse to 0.5 steps/s
            engine.on_event({"kind": "step", "step": i, "t": t})
            t += 2.0
        dips = anomaly_events(log.path, "throughput_dip")
        assert dips, "sustained slowdown must fire the dip detector"
        assert dips[0]["value"] < dips[0]["baseline"]
        log.close()

    def test_single_pause_does_not_dip(self, tmp_path):
        """One long gap (an eval epoch) must not burn the budget."""
        log, engine = make_engine(tmp_path, dip_factor=3.0)
        t = 1000.0
        for i in range(10):
            engine.on_event({"kind": "step", "step": i, "t": t})
            t += 0.1
        t += 30.0  # one eval-sized pause
        for i in range(10, 14):  # back to full speed
            engine.on_event({"kind": "step", "step": i, "t": t})
            t += 0.1
        assert anomaly_events(log.path, "throughput_dip") == []
        log.close()

    def test_stall_event_becomes_anomaly(self, tmp_path):
        log, _ = make_engine(tmp_path)
        log.stall(last_step=7, since_progress_s=1.5, deadline_s=0.5)
        (ev,) = anomaly_events(log.path, "stall")
        assert ev["value"] == 1.5 and ev["threshold"] == 0.5
        # heartbeats alone never fire it
        log.heartbeat(last_step=8, since_progress_s=0.1)
        assert len(anomaly_events(log.path, "stall")) == 1
        log.close()

    def test_unexpected_retrace_becomes_anomaly(self, tmp_path):
        log, _ = make_engine(tmp_path)
        log.compile_event("step", (1, 128), 0.5, count=1, unexpected=False)
        assert anomaly_events(log.path) == []  # expected compiles: quiet
        log.compile_event("step", (1, 128), 0.4, count=2, unexpected=True)
        (ev,) = anomaly_events(log.path, "unexpected_retrace")
        assert ev["fn"] == "step" and ev["compile_count"] == 2
        # the rolling compile-share context rides every anomaly event
        assert ev["compile_share"] is not None and ev["compile_share"] > 0
        log.close()

    def test_memory_watermark_growth_fires_plateau_does_not(self, tmp_path):
        log, _ = make_engine(
            tmp_path, watermark_factor=1.5, watermark_min_delta=1000.0
        )
        mb = 1 << 20
        for _ in range(5):  # flat watermark: quiet
            log.heartbeat(last_step=1, mem_peak_bytes=100 * mb)
        assert anomaly_events(log.path) == []
        log.heartbeat(last_step=2, mem_peak_bytes=170 * mb)  # 1.7x
        (ev,) = anomaly_events(log.path, "memory_watermark")
        assert ev["value"] == 170 * mb and ev["baseline"] == 100 * mb
        # re-armed at the fired level: the same plateau stays quiet...
        log.heartbeat(last_step=3, mem_peak_bytes=171 * mb)
        assert len(anomaly_events(log.path, "memory_watermark")) == 1
        log.close()

    def test_watermark_growth_survives_cooldown_suppression(self, tmp_path):
        """A growth observation whose _fire was suppressed by cooldown
        must NOT re-arm the baseline — once the cooldown expires the
        (still-standing) growth fires against the original baseline."""
        log, _ = make_engine(
            tmp_path, watermark_factor=1.5, watermark_min_delta=1000.0,
            cooldown_steps=4,
        )
        log.heartbeat(last_step=0, mem_peak_bytes=100_000)  # baseline
        log.heartbeat(last_step=1, mem_peak_bytes=200_000)  # fires, re-arms
        assert len(anomaly_events(log.path, "memory_watermark")) == 1
        log.heartbeat(last_step=2, mem_peak_bytes=400_000)  # cooldown: mute
        assert len(anomaly_events(log.path, "memory_watermark")) == 1
        for i in range(4):  # step events advance the cooldown clock
            log.step(i, wall_s=0.01, synced=True)
        log.heartbeat(last_step=6, mem_peak_bytes=400_000)  # plateau at 4x
        events = anomaly_events(log.path, "memory_watermark")
        assert len(events) == 2, "the muted growth must fire after cooldown"
        assert events[1]["baseline"] == 200_000.0  # not silently re-armed
        log.close()

    def test_anomaly_events_are_never_detector_input(self, tmp_path):
        """The engine's own output must not feed back into detection
        (a spike anomaly creating more anomalies forever)."""
        log, engine = make_engine(tmp_path)
        for i in range(10):
            log.step(i, wall_s=0.01, synced=True)
        log.step(10, wall_s=0.5, synced=True)
        n = len(anomaly_events(log.path))
        time.sleep(0.02)
        assert len(anomaly_events(log.path)) == n
        log.close()


class TestSloBurnDetector:
    """The slo_burn detector reacts to SloTracker transition events —
    the full service-level loop (forced-slow dispatch -> one anomaly +
    reactions) is pinned in tests/test_serve_obs.py; these are the
    engine-side edges."""

    def test_burning_transition_fires_with_reactions(self, tmp_path):
        log, engine = make_engine(tmp_path)
        for i in range(3):
            log.step(i, wall_s=0.01, synced=True)
        log.event("slo", name="serve", burning=True, target_s=0.05,
                  budget=0.25, burn_short=4.0, burn_long=4.0,
                  threshold=1.5, latency_s=0.5)
        (ev,) = anomaly_events(log.path, "slo_burn")
        assert ev["value"] == 4.0 and ev["baseline"] == 1.5
        assert ev["target_s"] == 0.05
        assert ev["flight"] and os.path.exists(ev["flight"])
        log.close()

    def test_final_status_and_recovery_never_fire(self, tmp_path):
        log, engine = make_engine(tmp_path)
        # terminal status events are marked final — never detector input
        log.event("slo", name="serve", burning=True, final=True,
                  target_s=0.05, burn_short=9.0, burn_long=9.0)
        # a recovery transition is not an anomaly either
        log.event("slo", name="serve", burning=False, target_s=0.05,
                  burn_short=0.0, burn_long=0.0)
        assert anomaly_events(log.path, "slo_burn") == []
        log.close()

    def test_cooldown_bounds_flapping_slo(self, tmp_path):
        log, engine = make_engine(tmp_path, cooldown_steps=100)
        for i in range(3):
            log.step(i, wall_s=0.01, synced=True)
        for _ in range(4):  # a flapping tracker re-enters burning
            log.event("slo", name="serve", burning=True, target_s=0.05,
                      burn_short=4.0, burn_long=4.0, threshold=1.5)
        assert len(anomaly_events(log.path, "slo_burn")) == 1
        log.close()


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------

class TestFlight:
    def test_error_event_dumps_context(self, tmp_path):
        log, engine = make_engine(tmp_path)
        for i in range(5):
            log.step(i, wall_s=0.01, synced=True)
        assert not os.path.exists(engine.flight.path)  # healthy: no file
        log.error("driver.place", ValueError("boom"))
        assert os.path.exists(engine.flight.path)
        records = read_events(engine.flight.path)
        assert records[0]["kind"] == "flight_meta"
        assert records[0]["reason"] == "error"
        dumped_kinds = [r["kind"] for r in records[1:]]
        assert dumped_kinds.count("step") == 5  # the context came along
        assert "error" in dumped_kinds
        log.close()

    def test_ring_is_bounded_and_dumps_dedup(self, tmp_path):
        log, engine = make_engine(tmp_path, flight_capacity=8)
        for i in range(50):
            log.step(i, wall_s=0.01, synced=True)
        log.error("a", ValueError("x"))
        first = read_events(engine.flight.path)
        assert first[0]["events"] <= 8 + 1  # ring capacity bounds context
        log.step(50, wall_s=0.01, synced=True)
        log.error("b", ValueError("y"))
        records = read_events(engine.flight.path)
        metas = [r for r in records if r["kind"] == "flight_meta"]
        assert [m["dump"] for m in metas] == [1, 2]
        # the second dump carries only events SINCE the first
        second_steps = [
            r for r in records[len(first):] if r["kind"] == "step"
        ]
        assert [r["step"] for r in second_steps] == [50]
        log.close()

    def test_shared_run_id_keeps_per_process_flight_and_trace_names(
        self, tmp_path, monkeypatch
    ):
        """Under GIGAPATH_OBS_RUN_ID every rank's run FILE carries a
        -<host>-p<pid> suffix; the flight file and trace dirs must
        inherit it so concurrent ranks never interleave into one
        post-mortem artifact."""
        monkeypatch.delenv("GIGAPATH_OBS", raising=False)
        monkeypatch.setenv("GIGAPATH_OBS_RUN_ID", "mh-run-1")
        log = get_run_log("t", out_dir=str(tmp_path), echo=False,
                          probe_devices=False)
        stem = os.path.splitext(os.path.basename(log.path))[0]
        assert f"-p{os.getpid()}" in stem
        assert os.path.basename(log.flight.path) == f"flight-{stem}.jsonl"
        trace_dir = log.anomaly._next_trace_dir_locked("x")
        assert os.path.basename(trace_dir).startswith(f"{stem}-x-")
        log.close()

    def test_dump_budget_exhaustion(self, tmp_path):
        log, engine = make_engine(tmp_path, flight_max_dumps=2)
        for i in range(6):
            log.step(i, wall_s=0.01, synced=True)
            log.error(f"e{i}", ValueError("x"))
        metas = [
            r for r in read_events(engine.flight.path)
            if r["kind"] == "flight_meta"
        ]
        assert len(metas) == 2  # the flapping trigger hit the budget
        log.close()


# ---------------------------------------------------------------------------
# triggered profiler capture
# ---------------------------------------------------------------------------

class TestProfilerCapture:
    @pytest.mark.slow
    def test_anomaly_triggers_capture_that_stops_after_k_steps(self, tmp_path):
        """Slow tier: compiles inside an open jax.profiler trace. The
        default tier covers capture via the budget/flag tests and the
        driver acceptance test below."""
        log, engine = make_engine(
            tmp_path, capture_budget=2, capture_steps=2
        )
        fn = jax.jit(lambda x: (x * 2).sum())
        for i in range(10):
            log.step(i, wall_s=0.01, synced=True)
        log.step(10, wall_s=0.5, synced=True)  # spike -> arm capture
        (ev,) = anomaly_events(log.path, "step_time_spike")
        assert ev["trace_dir"]
        for i in range(11, 15):  # trace runs across the next K steps
            fn(jnp.ones((4,)))
            log.step(i, wall_s=0.01, synced=True)
        log.run_end(status="ok")
        assert engine.trace_dirs == [ev["trace_dir"]]
        files = glob.glob(os.path.join(ev["trace_dir"], "**", "*"),
                          recursive=True)
        assert any("xplane" in f for f in files), (
            "the capture must leave real trace files"
        )

    def test_capture_budget_exhaustion(self, tmp_path):
        """Two firing detectors, budget 1 -> exactly one trace dir."""
        log, engine = make_engine(
            tmp_path, capture_budget=1, capture_steps=1, cooldown_steps=2
        )
        for i in range(10):
            log.step(i, wall_s=0.01, synced=True)
        log.step(10, wall_s=0.5, synced=True)   # spike 1: captures
        for i in range(11, 16):
            log.step(i, wall_s=0.01, synced=True)
        log.step(16, wall_s=0.9, synced=True)   # spike 2: budget gone
        log.run_end(status="ok")
        spikes = anomaly_events(log.path, "step_time_spike")
        assert len(spikes) == 2
        assert len(engine.trace_dirs) == 1
        assert spikes[1]["trace_dir"] is None

    def test_profile_flag_captures_first_n_steps(self, tmp_path, monkeypatch):
        monkeypatch.delenv("GIGAPATH_OBS", raising=False)
        monkeypatch.setenv("GIGAPATH_PROFILE", "2")
        log = get_run_log("t", out_dir=str(tmp_path), echo=False,
                          probe_devices=False)
        engine = log.anomaly
        assert isinstance(engine, AnomalyEngine)
        for i in range(4):
            log.step(i, wall_s=0.01, synced=True)
        log.run_end(status="ok")
        assert len(engine.trace_dirs) == 1
        assert "profile_flag" in engine.trace_dirs[0]
        assert os.path.isdir(engine.trace_dirs[0])


# ---------------------------------------------------------------------------
# zero-overhead / obs-off contracts
# ---------------------------------------------------------------------------

class TestZeroOverhead:
    def test_obs_off_means_no_engine_no_files(self, tmp_path, monkeypatch):
        monkeypatch.setenv("GIGAPATH_OBS", "0")
        log = get_run_log("t", out_dir=str(tmp_path))
        assert isinstance(log, NullRunLog) and not isinstance(log, RunLog)
        assert isinstance(attach_anomaly_engine(log), NullAnomalyEngine)
        for i in range(12):
            log.step(i, wall_s=0.01 if i != 10 else 9.9, synced=True)
        log.error("x", ValueError("boom"))
        log.run_end(status="ok")
        assert list(tmp_path.iterdir()) == [], "obs-off left artifacts"

    def test_anomaly_off_keeps_obs_on(self, tmp_path, monkeypatch):
        monkeypatch.delenv("GIGAPATH_OBS", raising=False)
        monkeypatch.setenv("GIGAPATH_ANOMALY", "0")
        log = get_run_log("t", out_dir=str(tmp_path), echo=False,
                          probe_devices=False)
        assert isinstance(log, RunLog)
        assert isinstance(
            getattr(log, "anomaly", NullAnomalyEngine()), NullAnomalyEngine
        )
        for i in range(12):
            log.step(i, wall_s=0.01 if i != 10 else 9.9, synced=True)
        log.run_end(status="ok")
        events = read_events(log.path)
        assert [e for e in events if e["kind"] == "anomaly"] == []
        assert not glob.glob(str(tmp_path / "obs" / "flight-*"))
        assert not glob.glob(str(tmp_path / "obs" / "traces" / "*"))

    def test_engine_attached_adds_zero_retraces(self, tmp_path):
        """The full closed loop (engine + flight + spike firing) watches
        a jitted step that compiles exactly as often as the bare twin —
        the engine is pure host-side event consumption."""

        def step(params, x):
            return params["w"] * jnp.sum(x)

        params = {"w": jnp.float32(2.0)}
        buckets = [jnp.ones((1, 128)), jnp.ones((1, 256))]

        bare = jax.jit(step)
        for x in buckets * 6:
            bare(params, x)

        log, engine = make_engine(tmp_path)
        instrumented = jax.jit(step)
        wd = CompileWatchdog("step", log, fn=instrumented)
        wrapped = wd.wrap(instrumented)
        for i, x in enumerate(buckets * 6):
            wall = 0.01 if i != 10 else 0.7  # seed a spike mid-run
            wrapped(params, x)
            log.step(i, wall_s=wall, synced=True)
        log.run_end(status="ok")

        assert anomaly_events(log.path, "step_time_spike"), (
            "the spike must actually have fired for this test to bite"
        )
        assert bare._cache_size() == instrumented._cache_size() == 2
        assert sum(wd.compile_count.values()) == 2
        assert wd.unexpected_retraces == []

    def test_watched_hlo_identical_with_engine_attached(self, tmp_path):
        def step(params, x):
            return params["w"] * jnp.sum(x)

        params = {"w": jnp.float32(2.0)}
        x = jnp.ones((1, 128))
        bare = jax.jit(step)
        bare(params, x)

        log, _ = make_engine(tmp_path)
        watched = jax.jit(step)
        wd = CompileWatchdog("step", log, fn=watched)
        wrapped = wd.wrap(watched)
        wrapped(params, x)
        log.close()
        assert (
            bare.lower(params, x).compile().as_text()
            == watched.lower(params, x).compile().as_text()
        )


# ---------------------------------------------------------------------------
# heartbeat memory watermarks (satellite)
# ---------------------------------------------------------------------------

class TestHeartbeatWatermarks:
    def test_cpu_backend_heartbeats_carry_no_mem_fields(self, tmp_path):
        from gigapath_tpu.obs import Heartbeat

        log = RunLog(str(tmp_path / "run.jsonl"), driver="t", echo=False)
        with Heartbeat(log, interval_s=0.05, stall_after_s=10.0,
                       name="t") as hb:
            hb.beat(1)
            time.sleep(0.2)
        hbs = [ev for ev in read_events(log.path) if ev["kind"] == "heartbeat"]
        assert hbs
        assert all("mem_peak_bytes" not in ev for ev in hbs), (
            "CPU memory_stats() is None — the field must be absent, not 0"
        )
        log.close()

    def test_watermarks_ride_heartbeats_when_backend_reports(
        self, tmp_path, monkeypatch
    ):
        from gigapath_tpu.obs import Heartbeat

        class FakeDev:
            def __init__(self, peak, in_use):
                self._s = {"peak_bytes_in_use": peak, "bytes_in_use": in_use}

            def memory_stats(self):
                return self._s

        monkeypatch.setattr(
            jax, "devices", lambda: [FakeDev(300, 120), FakeDev(500, 80)]
        )
        log = RunLog(str(tmp_path / "run.jsonl"), driver="t", echo=False)
        with Heartbeat(log, interval_s=0.05, stall_after_s=10.0,
                       name="t") as hb:
            hb.beat(1)
            time.sleep(0.2)
        hbs = [ev for ev in read_events(log.path) if ev["kind"] == "heartbeat"]
        assert hbs
        assert hbs[-1]["mem_peak_bytes"] == 500.0   # max across devices
        assert hbs[-1]["mem_bytes_in_use"] == 200.0  # summed
        log.close()

    def test_memory_watermarks_helper_guards(self, monkeypatch):
        from gigapath_tpu.obs.heartbeat import memory_watermarks

        assert memory_watermarks() == {}  # CPU: stats are None

        def boom():
            raise RuntimeError("backend exploded")

        monkeypatch.setattr(jax, "devices", boom)
        assert memory_watermarks() == {}  # never raises into the beat

    def test_env_tunable_deadlines(self, monkeypatch):
        from gigapath_tpu.obs import Heartbeat

        monkeypatch.setenv("GIGAPATH_OBS_HEARTBEAT_S", "1.5")
        monkeypatch.setenv("GIGAPATH_OBS_STALL_S", "7.5")
        hb = Heartbeat(NullRunLog())
        assert hb.interval_s == 1.5 and hb.stall_after_s == 7.5
        explicit = Heartbeat(NullRunLog(), interval_s=9.0, stall_after_s=90.0)
        assert explicit.interval_s == 9.0  # explicit args win
        monkeypatch.setenv("GIGAPATH_OBS_STALL_S", "not-a-number")
        assert Heartbeat(NullRunLog()).stall_after_s == 300.0  # safe fallback


# ---------------------------------------------------------------------------
# acceptance: a real CPU driver run, closed loop end to end
# ---------------------------------------------------------------------------

def _feature_files(tmp_path, n_slides=4, n_tiles=12, dim=16):
    import torch

    rng = np.random.default_rng(0)
    feat_dir = tmp_path / "features"
    feat_dir.mkdir()
    for i in range(n_slides):
        torch.save(
            {
                "features": torch.from_numpy(
                    rng.normal(size=(n_tiles, dim)).astype(np.float32)
                ),
                "coords": torch.from_numpy(
                    rng.integers(0, 1000, (n_tiles, 2)).astype(np.float32)
                ),
            },
            feat_dir / f"s{i}_features.pt",
        )
    return str(feat_dir)


def _tiny_inference_model():
    from gigapath_tpu.inference import load_model

    return load_model(
        "", input_dim=16, latent_dim=32, feat_layer="1", n_classes=2,
        model_arch="gigapath_slide_enc_tiny",
    )


class _CompileCounter(logging.Handler):
    """Counts XLA compiles of the driver's jitted ``forward`` via
    jax_log_compiles — backend truth, independent of obs being on."""

    def __init__(self):
        super().__init__()
        self.count = 0

    def emit(self, record):
        msg = record.getMessage()
        if "Finished XLA compilation of jit(forward)" in msg:
            self.count += 1


def _run_inference_driver(tmp_path, monkeypatch, stall_slide=2,
                          stall_s=0.7):
    """Drive gigapath_tpu.inference over tiny synthetic slides, forcing
    a stall (slow feature load) on one slide. Returns the compile count
    observed at the XLA layer."""
    import gigapath_tpu.inference as inference

    feat_dir = _feature_files(tmp_path)
    model, params = _tiny_inference_model()

    real_load = inference._load_features
    calls = {"n": 0}

    def slow_load(path):
        calls["n"] += 1
        if calls["n"] == stall_slide + 1:
            time.sleep(stall_s)  # the forced stall: one hung "RPC"
        return real_load(path)

    monkeypatch.setattr(inference, "_load_features", slow_load)

    counter = _CompileCounter()
    logger = logging.getLogger("jax._src.dispatch")
    logger.addHandler(counter)
    prev_level = logger.level
    logger.setLevel(logging.DEBUG)
    jax.config.update("jax_log_compiles", True)
    try:
        out_csv = str(tmp_path / "out" / "predictions.csv")
        os.makedirs(os.path.dirname(out_csv), exist_ok=True)
        # exact-shape path: this acceptance pair pins the slide-at-a-time
        # driver's compile accounting (the bucketed serving path has its
        # own compile-count pins in tests/test_serve.py)
        df = inference.run_inference(model, params, feat_dir, out_csv,
                                     use_buckets=False)
    finally:
        jax.config.update("jax_log_compiles", False)
        logger.setLevel(prev_level)
        logger.removeHandler(counter)
    assert df is not None and len(df) == 4
    return counter.count


def test_inference_driver_stall_produces_anomaly_flight_and_trace(
    tmp_path, monkeypatch
):
    """ISSUE 6 acceptance (tier-1 by requirement): a forced stall in a
    CPU driver run produces an anomaly event, a flight dump and
    (capture enabled) a profiler trace dir."""
    monkeypatch.delenv("GIGAPATH_OBS", raising=False)
    monkeypatch.delenv("GIGAPATH_ANOMALY", raising=False)
    monkeypatch.setenv("GIGAPATH_OBS_HEARTBEAT_S", "0.05")
    monkeypatch.setenv("GIGAPATH_OBS_STALL_S", "0.2")
    monkeypatch.setenv("GIGAPATH_PROFILE", "1")  # capture from step 1 too

    compiles = _run_inference_driver(tmp_path, monkeypatch)

    obs_dir = tmp_path / "out" / "obs"
    runs = glob.glob(str(obs_dir / "inference-*.jsonl"))
    runs = [p for p in runs if "flight-" not in os.path.basename(p)]
    assert len(runs) == 1
    events = read_events(runs[0])
    kinds = {ev["kind"] for ev in events}
    assert {"run_start", "step", "compile", "stall", "anomaly",
            "run_end"} <= kinds

    # 1) the anomaly event (stall detector)
    stall_anomalies = [
        ev for ev in events
        if ev["kind"] == "anomaly" and ev["detector"] == "stall"
    ]
    assert stall_anomalies, "the forced stall must fire the detector"

    # 2) the flight dump, carrying the context around the stall (the
    # first stall fires during the first slide's compile, so the buffer
    # holds the run_start/heartbeat prefix — context, whatever it was)
    flights = glob.glob(str(obs_dir / "flight-*.jsonl"))
    assert len(flights) == 1
    flight_records = read_events(flights[0])
    assert flight_records[0]["kind"] == "flight_meta"
    assert flight_records[0]["reason"] == "stall"
    assert len(flight_records) > 1, "the dump must carry context events"

    # 3) the profiler trace dir(s), with real trace files inside
    trace_dirs = glob.glob(str(obs_dir / "traces" / "*"))
    assert trace_dirs, "GIGAPATH_PROFILE=1 must leave a capture dir"
    trace_files = glob.glob(str(obs_dir / "traces" / "**" / "*"),
                            recursive=True)
    assert any("xplane" in f for f in trace_files)

    # compile accounting: every slide shares one shape -> one jit
    # compile, plus exactly the ledger's documented one-off AOT profile
    # compile; the watchdog saw no unexpected retraces
    compile_events = [ev for ev in events if ev["kind"] == "compile"]
    assert len(compile_events) == 1
    assert not any(ev.get("unexpected") for ev in compile_events)
    assert compiles == 2  # jit + ledger full-profile AOT (and nothing else)

    # obs_report renders the anomalies section from the artifact
    import obs_report

    import io

    buf = io.StringIO()
    assert obs_report.render(read_events(runs[0]), out=buf) == 0
    text = buf.getvalue()
    assert "== anomalies ==" in text and "STALL" in text


def test_inference_driver_obs_off_twin_is_silent_and_compiles_the_same(
    tmp_path, monkeypatch
):
    """The obs-off twin of the run above: same forced stall, no anomaly
    event, no flight file, no trace dir anywhere in the tree — and the
    same XLA compile count minus exactly the ledger's documented AOT
    profile (i.e. zero retraces either way)."""
    monkeypatch.setenv("GIGAPATH_OBS", "0")
    monkeypatch.setenv("GIGAPATH_OBS_HEARTBEAT_S", "0.05")
    monkeypatch.setenv("GIGAPATH_OBS_STALL_S", "0.2")
    monkeypatch.setenv("GIGAPATH_PROFILE", "1")  # must be inert when obs off

    compiles = _run_inference_driver(tmp_path, monkeypatch)

    # none of the three artifacts exist anywhere under the test tree
    left = [
        os.path.relpath(p, str(tmp_path))
        for p in glob.glob(str(tmp_path / "**" / "*"), recursive=True)
        if os.path.isfile(p)
    ]
    parts = {seg for p in left for seg in p.split(os.sep)}
    assert "obs" not in parts and "traces" not in parts, left
    assert not any(seg.startswith("flight-") for seg in parts), left
    assert not any("anomaly" in p for p in left), left
    assert [os.path.basename(p) for p in left].count("predictions.csv") == 1
    # 4 same-shape slides -> exactly ONE compile of forward: obs-on adds
    # only the ledger AOT profile (pinned at exactly +1 by the twin
    # test), never a retrace
    assert compiles == 1
