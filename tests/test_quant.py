"""Quantized tile-encoder subsystem (gigapath_tpu/quant/) tests.

The acceptance pins (ISSUE 14):

- int8 parity on the committed fixture weights: embedding cosine >=
  0.999 vs the f32 oracle, PCam-recipe linear-probe accuracy delta <=
  0.5 pt, asserted here in tier-1;
- converter round-trip (quantize -> dequantize within per-channel scale
  bounds, re-quantization bit-exact) and corrupt-artifact refusal via
  the manifest;
- flag-on/flag-off are DISTINCT traced programs (distinct jit keys) and
  the quant tier pays zero unexpected retraces (watchdog-pinned, the
  PR-12 discipline);
- the disaggregated dryrun runs the REAL quantized encoder behind
  ``dist/worker.py``'s ``encode`` seam with kill-recover bit-exactness;
- the ledger fingerprint's ``quant`` column pins the tier's op mix;
- one shared bf16 embedding-quantize helper (the dense/streaming/dist
  dedup) with a parity pin.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gigapath_tpu.quant import parity
from gigapath_tpu.quant.convert import (
    CorruptQuantArtifact,
    dequantize_params,
    load_quantized,
    quantize_params,
    save_quantized,
)
from gigapath_tpu.quant.qtensor import (
    QTensor,
    bf16_round_trip,
    dequantize,
    normalize_mode,
    quantize_per_channel,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def fixture_data():
    return parity.load_fixture()


# ---------------------------------------------------------------------------
# qtensor: the sanctioned helper set
# ---------------------------------------------------------------------------

class TestQTensor:
    def test_int8_dequant_within_per_channel_scale_bounds(self):
        rng = np.random.default_rng(0)
        w = rng.standard_normal((64, 32)).astype(np.float32)
        qt = quantize_per_channel(w, "int8")
        err = np.abs(np.asarray(dequantize(qt)) - w)
        # rounding to the per-channel grid: error <= scale/2 per element
        bound = np.broadcast_to(np.asarray(qt.scale) / 2 + 1e-7, w.shape)
        assert (err <= bound).all()

    @pytest.mark.parametrize("mode", ["int8", "fp8_e4m3"])
    def test_requantization_is_idempotent(self, mode):
        """quantize(dequantize(q)) == q bit-exactly — the converter's
        no-drift guarantee."""
        rng = np.random.default_rng(1)
        w = rng.standard_normal((32, 16)).astype(np.float32)
        qt = quantize_per_channel(w, mode)
        qt2 = quantize_per_channel(np.asarray(dequantize(qt)), mode)
        assert np.array_equal(
            np.asarray(qt.data).view(np.uint8),
            np.asarray(qt2.data).view(np.uint8),
        )
        assert np.array_equal(np.asarray(qt.scale), np.asarray(qt2.scale))

    def test_zero_channel_stays_exact_zero(self):
        w = np.zeros((8, 4), np.float32)
        w[:, 1] = 3.0
        qt = quantize_per_channel(w, "int8")
        deq = np.asarray(dequantize(qt))
        assert (deq[:, 0] == 0).all() and np.isfinite(deq).all()

    def test_normalize_mode(self):
        assert normalize_mode("") == ""
        assert normalize_mode("1") == "int8"
        assert normalize_mode("INT8") == "int8"
        assert normalize_mode("fp8") == "fp8_e4m3"
        assert normalize_mode("int8+attn") == "int8+attn"
        with pytest.raises(ValueError):
            normalize_mode("int4")

    def test_bf16_round_trip_is_the_dense_entry_quantization(self):
        """The shared helper == the dense slide entry's inline bf16
        cast (the dedup pin: dense, streaming and dist paths all feed
        the slide encoder bit-identical inputs)."""
        rng = np.random.default_rng(2)
        x = rng.standard_normal((16, 8)).astype(np.float32)
        inline = np.asarray(jnp.asarray(x, jnp.bfloat16).astype(jnp.float32))
        assert np.array_equal(bf16_round_trip(x), inline)
        # idempotent: already-rounded values pass through bit-exactly
        assert np.array_equal(bf16_round_trip(bf16_round_trip(x)),
                              bf16_round_trip(x))


# ---------------------------------------------------------------------------
# qmatmul / qflash tiers
# ---------------------------------------------------------------------------

class TestQMatmul:
    def test_reference_close_to_f32(self):
        from gigapath_tpu.quant.qmatmul import q_matmul

        rng = np.random.default_rng(3)
        w = rng.standard_normal((128, 64)).astype(np.float32)
        x = rng.standard_normal((4, 128)).astype(np.float32)
        qt = quantize_per_channel(w, "int8")
        y = np.asarray(q_matmul(jnp.asarray(x), qt))
        ref = x @ w
        assert np.abs(y - ref).max() <= 0.02 * np.abs(ref).max()

    def test_pallas_tier_matches_reference(self):
        from gigapath_tpu.quant.qmatmul import q_matmul_pallas, q_matmul_reference

        rng = np.random.default_rng(4)
        w = rng.standard_normal((256, 128)).astype(np.float32)
        x = rng.standard_normal((8, 256)).astype(np.float32)
        qt = quantize_per_channel(w, "int8")
        ref = np.asarray(q_matmul_reference(jnp.asarray(x), qt))
        pal = np.asarray(q_matmul_pallas(jnp.asarray(x), qt, interpret=True))
        np.testing.assert_allclose(pal, ref, atol=1e-5, rtol=1e-5)

    def test_quant_dense_param_surface_matches_nn_dense(self):
        """QuantDense declares the exact nn.Dense param names/shapes, so
        checkpoints and the sharding-rule name lists are oblivious."""
        from flax import linen as nn

        from gigapath_tpu.quant.qmatmul import QuantDense

        x = jnp.ones((2, 16))
        dense = nn.Dense(8, name="fc1")
        qdense = QuantDense(8, mode="int8", name="fc1")
        p1 = dense.init(jax.random.PRNGKey(0), x)["params"]
        p2 = qdense.init(jax.random.PRNGKey(0), x)["params"]
        assert set(p1) == set(p2) == {"kernel", "bias"}
        assert all(p1[k].shape == p2[k].shape for k in p1)
        # and an nn.Dense param tree applies straight through
        out = qdense.apply({"params": p1}, x)
        assert out.shape == (2, 8)


class TestQFlash:
    def test_reference_close_to_f32_oracle(self):
        from gigapath_tpu.ops.attention import attention_with_lse
        from gigapath_tpu.quant.qflash import q_flash_attention_reference

        rng = np.random.default_rng(5)
        q, k, v = (
            jnp.asarray(rng.standard_normal((2, 64, 4, 16)), jnp.float32)
            for _ in range(3)
        )
        out_q, lse_q = q_flash_attention_reference(q, k, v)
        out_f, lse_f = attention_with_lse(q, k, v)
        assert parity.mean_cosine(
            np.asarray(out_q).reshape(-1, 16),
            np.asarray(out_f).reshape(-1, 16),
        ) >= 0.999
        np.testing.assert_allclose(
            np.asarray(lse_q), np.asarray(lse_f), atol=0.05
        )

    def test_pallas_tier_matches_reference(self):
        from gigapath_tpu.quant.qflash import (
            q_flash_attention_pallas,
            q_flash_attention_reference,
        )

        rng = np.random.default_rng(6)
        q, k, v = (
            jnp.asarray(rng.standard_normal((1, 128, 2, 32)), jnp.float32)
            for _ in range(3)
        )
        out_r, lse_r = q_flash_attention_reference(q, k, v)
        out_p, lse_p = q_flash_attention_pallas(q, k, v, interpret=True)
        np.testing.assert_allclose(
            np.asarray(out_p), np.asarray(out_r), atol=5e-3
        )
        np.testing.assert_allclose(
            np.asarray(lse_p), np.asarray(lse_r), atol=1e-4
        )


# ---------------------------------------------------------------------------
# converter + artifact
# ---------------------------------------------------------------------------

class TestConverter:
    def test_quantize_params_targets_dense_kernels_only(self, fixture_data):
        params, _, _ = fixture_data
        qparams = quantize_params(params, "int8")
        qkv = qparams["blocks_0"]["attn"]["qkv"]["kernel"]
        assert isinstance(qkv, QTensor) and qkv.data.dtype == np.int8
        # conv patch embed (4-D) and biases stay full precision
        assert not isinstance(
            qparams["patch_embed"]["proj"]["kernel"], QTensor
        )
        assert not isinstance(
            qparams["blocks_0"]["attn"]["qkv"]["bias"], QTensor
        )

    @pytest.mark.parametrize("mode", ["int8", "fp8_e4m3"])
    def test_artifact_roundtrip_bitexact(self, tmp_path, mode, fixture_data):
        params, _, _ = fixture_data
        qparams = quantize_params(params, mode)
        path = save_quantized(
            str(tmp_path / "artifact"), qparams, meta={"arch": "test"}
        )
        loaded, meta = load_quantized(path)
        assert meta["mode"] == mode and meta["arch"] == "test"
        assert meta["n_quantized"] > 0
        flat_a = dict(_walk_pairs(qparams))
        flat_b = dict(_walk_pairs(loaded))
        assert set(flat_a) == set(flat_b)
        for key, leaf in flat_a.items():
            other = flat_b[key]
            if isinstance(leaf, QTensor):
                assert np.array_equal(
                    np.asarray(leaf.data).view(np.uint8),
                    np.asarray(other.data).view(np.uint8),
                )
                assert np.array_equal(leaf.scale, other.scale)
            else:
                assert np.array_equal(leaf, other)

    def test_corrupt_artifact_refused(self, tmp_path, fixture_data):
        params, _, _ = fixture_data
        qparams = quantize_params(params, "int8")
        path = save_quantized(str(tmp_path / "artifact"), qparams)
        # flip one byte of the array payload: the manifest re-hash must
        # refuse the load — never silently-wrong scales
        target = os.path.join(path, "arrays.npz")
        blob = bytearray(open(target, "rb").read())
        blob[len(blob) // 2] ^= 0xFF
        with open(target, "wb") as fh:
            fh.write(bytes(blob))
        with pytest.raises(CorruptQuantArtifact):
            load_quantized(path)

    def test_unexpected_extra_file_refused(self, tmp_path, fixture_data):
        """An extra file the manifest never hashed is a refused load
        too (the checkpointer's exact-tree discipline); verify=False is
        the explicit opt-out."""
        params, _, _ = fixture_data
        path = save_quantized(
            str(tmp_path / "artifact"), quantize_params(params, "int8")
        )
        with open(os.path.join(path, "stray.bin"), "wb") as fh:
            fh.write(b"not in the manifest")
        with pytest.raises(CorruptQuantArtifact):
            load_quantized(path)
        load_quantized(path, verify=False)

    def test_missing_file_refused(self, tmp_path, fixture_data):
        params, _, _ = fixture_data
        path = save_quantized(
            str(tmp_path / "artifact"), quantize_params(params, "int8")
        )
        os.remove(os.path.join(path, "meta.json"))
        with pytest.raises(CorruptQuantArtifact):
            load_quantized(path)

    def test_create_tile_encoder_loads_artifact(self, tmp_path, fixture_data):
        from gigapath_tpu.models.tile_encoder import create_tile_encoder

        params, images, _ = fixture_data
        path = save_quantized(
            str(tmp_path / "artifact"), quantize_params(params, "int8")
        )
        model, loaded = create_tile_encoder(path, "vit_tile_enc_test")
        ref = parity.encode(model, dequantize_params(
            quantize_params(params, "int8")), images[:4])
        got = parity.encode(model, loaded, images[:4])
        np.testing.assert_array_equal(got, ref)


def _walk_pairs(tree, prefix=()):
    for key in sorted(tree):
        value = tree[key]
        if isinstance(value, dict):
            yield from _walk_pairs(value, prefix + (key,))
        else:
            yield "/".join(prefix + (key,)), value


# ---------------------------------------------------------------------------
# the acceptance: parity on the committed fixture weights
# ---------------------------------------------------------------------------

class TestParityAcceptance:
    @pytest.fixture(scope="class")
    def report(self, fixture_data):
        params, images, labels = fixture_data
        return parity.parity_report(
            params, images, labels,
            variants=("bf16", "int8", "fp8_e4m3", "int8+attn"),
        )

    def test_int8_cosine_and_probe_delta(self, report):
        """THE acceptance bars: cosine >= 0.999 vs the f32 oracle and
        |probe delta| <= 0.5 pt, on CPU, in tier-1."""
        int8 = report["variants"]["int8"]
        assert int8["cosine"] >= parity.COSINE_BAR, int8
        assert abs(int8["probe_delta_pt"]) <= parity.PROBE_DELTA_BAR_PT, int8

    def test_fp8_and_attn_riders_hold_parity(self, report):
        for name in ("fp8_e4m3", "int8+attn"):
            var = report["variants"][name]
            assert var["cosine"] >= parity.COSINE_BAR, (name, var)

    def test_probe_has_signal(self, report):
        # a probe at chance would make the delta bar vacuous
        assert report["oracle"]["probe_acc"] >= 0.9

    def test_decision_table_gates(self, report):
        # parity-only (CPU): never adopts, but parity_ok is visible
        cpu_row = parity.decision_table(report)
        assert cpu_row["parity_ok"] is True
        assert cpu_row["adopt_quant_tile"] is False
        # with a measured >=3% win: adopts
        fast = parity.decision_table(
            report, {"bf16": 0.010, "int8": 0.008})
        assert fast["adopt_quant_tile"] is True
        # with a measured loss: refuses
        slow = parity.decision_table(
            report, {"bf16": 0.010, "int8": 0.011})
        assert slow["adopt_quant_tile"] is False and slow["parity_ok"]


# ---------------------------------------------------------------------------
# flag routing, jit keys, retraces, ledger column
# ---------------------------------------------------------------------------

class TestFlagRouting:
    def test_snapshot_reads_quant_flags(self, monkeypatch):
        from gigapath_tpu.ops.pallas_dilated import snapshot_flags

        monkeypatch.delenv("GIGAPATH_QUANT_TILE", raising=False)
        monkeypatch.delenv("GIGAPATH_QUANT_PALLAS", raising=False)
        flags = snapshot_flags()
        assert flags.quant_tile == "" and flags.quant_pallas is False
        monkeypatch.setenv("GIGAPATH_QUANT_TILE", "int8")
        monkeypatch.setenv("GIGAPATH_QUANT_PALLAS", "1")
        flags = snapshot_flags()
        assert flags.quant_tile == "int8" and flags.quant_pallas is True

    def test_flag_on_off_are_distinct_traced_programs(self, fixture_data):
        """Quant on/off must land in distinct jit cache entries — the
        flag changes WHICH program is built (model config), so there is
        no jit-cache staleness hazard to begin with."""
        params, images, _ = fixture_data
        x = jnp.asarray(images[:2])
        off = parity.build_variant(parity.FIXTURE_ARCH)
        on = parity.build_variant(parity.FIXTURE_ARCH, quant="int8")
        jx_off = jax.make_jaxpr(
            lambda p, x: off.apply({"params": p}, x))(params, x)
        jx_on = jax.make_jaxpr(
            lambda p, x: on.apply({"params": p}, x))(params, x)
        assert str(jx_off) != str(jx_on)

    def test_ledger_quant_column_pins_the_op_mix(self, fixture_data):
        """quant-on programs must SHOW low-precision eqns; quant-off
        must show zero — the fingerprint column that makes a silently-
        f32 'quant' tier a ledger regression."""
        from gigapath_tpu.obs.ledger import jaxpr_fingerprint

        params, images, _ = fixture_data
        x = jnp.asarray(images[:2])
        off = parity.build_variant(parity.FIXTURE_ARCH)
        on = parity.build_variant(parity.FIXTURE_ARCH, quant="int8")
        fp_off = jaxpr_fingerprint(
            lambda p, x: off.apply({"params": p}, x), params, x)
        fp_on = jaxpr_fingerprint(
            lambda p, x: on.apply({"params": p}, x), params, x)
        assert fp_off["quant"] == 0
        assert fp_on["quant"] > 0
        # the column is NOT a primitive and never feeds eqns_total
        assert "quant" not in fp_on["primitives"]

    def test_quant_tier_zero_unexpected_retraces(self, tmp_path,
                                                 fixture_data):
        """Watchdog-pinned (the PR-12 seed-sharding discipline): a
        batch loop over the quant tier compiles ONCE and every later
        batch hits the same entry."""
        from gigapath_tpu.obs.runlog import RunLog
        from gigapath_tpu.obs.watchdog import CompileWatchdog

        params, images, _ = fixture_data
        model = parity.build_variant(parity.FIXTURE_ARCH, quant="int8")

        @jax.jit
        def encode(p, x):
            return model.apply({"params": p}, x)

        log = RunLog(str(tmp_path / "run.jsonl"), driver="t", echo=False)
        watchdog = CompileWatchdog("quant.encode", log)
        wrapped = watchdog.wrap(encode)
        for start in (0, 8, 16):
            wrapped(params, jnp.asarray(images[start:start + 8]))
        assert encode._cache_size() == 1, "the quant tier retraced"
        log.close()


# ---------------------------------------------------------------------------
# dist: the REAL quantized encoder behind the encode seam
# ---------------------------------------------------------------------------

class TestDistQuantEncoder:
    def _plan(self, **kw):
        from gigapath_tpu.dist.pipeline import default_plan

        return default_plan(
            n_tiles=32, chunk_tiles=8, dim_in=16, dim_out=8,
            lease_s=1.5, credits=4, retransmit_s=0.5,
            encoder="quant_vit", quant="int8", **kw,
        )

    def test_make_encoder_is_deterministic_and_bf16_rounded(self):
        from gigapath_tpu.dist.worker import make_encoder

        plan = self._plan()
        a, coords_a = make_encoder(plan)(0, 8)
        b, coords_b = make_encoder(plan)(0, 8)
        assert np.array_equal(a, b) and np.array_equal(coords_a, coords_b)
        assert a.shape == (8, 8) and a.dtype == np.float32
        # the shared bf16 helper ran: the payload is already on the
        # bf16 grid (the dense/streaming/dist input-parity contract)
        assert np.array_equal(a, bf16_round_trip(a))

    def test_make_encoder_handles_ragged_tail_chunk(self):
        """n_tiles not a chunk multiple: the tail shape is warmed too
        and encodes fine (the mid-lease-compile hazard class)."""
        from gigapath_tpu.dist.worker import make_encoder

        plan = self._plan()
        plan["n_tiles"] = 28  # chunks of 8 -> ragged tail of 4
        embeds, coords = make_encoder(plan)(24, 28)
        assert embeds.shape == (4, 8) and coords.shape == (4, 2)

    def test_make_encoder_rejects_unknown_encoder(self):
        from gigapath_tpu.dist.worker import make_encoder

        plan = self._plan()
        plan["encoder"] = "quantvit"  # typo must be LOUD, never dryrun
        with pytest.raises(ValueError):
            make_encoder(plan)

    def test_dryrun_runs_real_quant_encoder_with_kill_recover(self, tmp_path):
        """THE dist acceptance: one disaggregated dryrun (two real
        worker processes) with the quant_vit encoder and a SIGKILLed
        worker — the full assembled embedding matrix must equal the
        in-process quantized encoder's output BIT-exactly (the seam ran
        the real encoder; reassignment re-encoded the dead worker's
        chunks to identical bits)."""
        from gigapath_tpu.dist.pipeline import run_disaggregated
        from gigapath_tpu.dist.worker import make_encoder, plan_chunks

        plan = self._plan()
        result = run_disaggregated(
            str(tmp_path / "dryrun"), plan=plan,
            worker_chaos={"w0": "kill_worker@1"}, deadline_s=150,
        )
        assert result["worker_exit_codes"]["w0"] == -9, (
            result["worker_exit_codes"]
        )
        assert result["lost"] == ["w0"] and result["reassignments"] >= 1
        encode = make_encoder(plan)
        expected = np.concatenate([
            encode(start, stop)[0]
            for _, start, stop in plan_chunks(plan["n_tiles"],
                                              plan["chunk_tiles"])
        ])
        assert np.array_equal(result["assembled"], expected), (
            "kill-recover assembly diverges from the in-process "
            "quantized encoder"
        )


# ---------------------------------------------------------------------------
# perf-history fold
# ---------------------------------------------------------------------------

class TestTileQuantTrend:
    def test_fold_tile_stale_with_keys_on_cpu(self):
        from gigapath_tpu.obs import history

        doc = history.new_history()
        point = history.fold_tile(
            doc,
            {"rc": 0, "parsed": {"backend": "cpu",
                                 "int8_tiles_per_sec": 10.0,
                                 "cosine_drift": 1e-5,
                                 "probe_delta_pt": 0.0}},
            "r01",
        )
        assert point["stale"] and "cosine_drift" in point["metrics"]
        assert "tile|quant" in doc["entries"]

    def test_direction_rules(self):
        from gigapath_tpu.obs.history import metric_direction

        assert metric_direction("int8_tiles_per_sec") == "up"
        assert metric_direction("cosine_drift") == "down"
        assert metric_direction("probe_delta_pt") == "down"
