"""Streaming chunked prefill: fold tile chunks, never materialize the
slide sequence (ISSUE 12's acceptance surface).

Four contracts, each pinned here:

1. **Exactness** — streaming dilated attention matches the dense oracle
   at fwd 1e-5 / grads 1e-4 (ragged final chunk, single-chunk
   degenerate case included), and the chunk-granular ``LongNetViT``
   session matches ``model.apply`` for cls AND global-pool readout.
2. **Order independence** — permuted (dist out-of-order) chunk delivery
   is BIT-exact vs in-order delivery: the fold frontier, not the
   network, fixes the op sequence.
3. **Memory boundedness** — XLA memory analysis of the per-chunk fold
   executable: temp/peak bytes FLAT as the chunk count grows (4x the
   length at a fixed chunk size) and < 0.6x the dense program at the
   16k smoke geometry; plus the jaxpr guard — zero full-sequence-length
   avals anywhere in the fold program (the dense path is the positive
   control for the guard's teeth).
4. **Serving surface** — the serve streaming submitter and the
   ``pipeline`` chunk-iterator entry reproduce the dense
   ``run_inference_with_slide_encoder`` outputs.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gigapath_tpu.models.slide_encoder import LongNetViT
from gigapath_tpu.models.streaming_encoder import (
    StreamingEncoderSession,
    streaming_forward,
)
from gigapath_tpu.ops.dilated_attention import dilated_attention
from gigapath_tpu.ops.streaming_prefill import (
    StreamingPrefillState,
    assemble_dense_fallback,
    chunk_bounds,
    fold_pair,
    fold_plan,
    full_length_avals,
    streaming_dilated_attention,
)

SCHED = ([16, 32, 128], [1, 2, 4])


def _qkv(rng, L, H=4, Dh=8):
    return tuple(
        jnp.asarray(rng.normal(size=(1, L, H, Dh)), jnp.float32)
        for _ in range(3)
    )


def _blocks(x, bounds):
    return [x[:, a:b] for a, b in bounds]


class TestOpParity:
    def test_forward_matches_dense_with_ragged_tail(self, rng):
        L = 67  # 24, 24, 19: a ragged final chunk by construction
        q, k, v = _qkv(rng, L)
        sls, drs = SCHED
        dense = dilated_attention(q, k, v, sls, drs).astype(jnp.float32)
        bounds = chunk_bounds(L, 24)
        blocks = streaming_dilated_attention(
            _blocks(q, bounds), _blocks(k, bounds), _blocks(v, bounds),
            bounds, sls, drs,
        )
        assert [b.shape[1] for b in blocks] == [24, 24, 19]
        np.testing.assert_allclose(
            np.asarray(assemble_dense_fallback(blocks)), np.asarray(dense),
            atol=1e-5, rtol=0,
        )

    def test_single_chunk_degenerate(self, rng):
        L = 40
        q, k, v = _qkv(rng, L)
        sls, drs = SCHED
        dense = dilated_attention(q, k, v, sls, drs).astype(jnp.float32)
        blocks = streaming_dilated_attention(
            [q], [k], [v], [(0, L)], sls, drs,
        )
        assert len(blocks) == 1
        np.testing.assert_allclose(
            np.asarray(blocks[0]), np.asarray(dense), atol=1e-5, rtol=0,
        )

    def test_grads_match_dense(self, rng):
        L = 48
        q, k, v = _qkv(rng, L, H=2, Dh=4)
        sls, drs = [8, 64], [1, 2]
        bounds = chunk_bounds(L, 16)

        def dense_loss(q, k, v):
            o = dilated_attention(q, k, v, sls, drs)
            return (o.astype(jnp.float32) ** 2).sum()

        def stream_loss(q, k, v):
            blocks = streaming_dilated_attention(
                _blocks(q, bounds), _blocks(k, bounds), _blocks(v, bounds),
                bounds, sls, drs, jit_pairs=False,
            )
            return sum((blk ** 2).sum() for blk in blocks)

        gd = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
        gs = jax.grad(stream_loss, argnums=(0, 1, 2))(q, k, v)
        for name, a, b in zip("qkv", gd, gs):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-4, rtol=0,
                err_msg=f"grad d{name} diverges",
            )

    def test_in_order_contract_enforced(self, rng):
        q, k, v = _qkv(rng, 32)
        state = StreamingPrefillState(chunk_bounds(32, 16), [16], [1])
        with pytest.raises(ValueError, match="index order"):
            state.ingest(1, q[:, 16:], k[:, 16:], v[:, 16:])

    def test_fold_plan_locality(self):
        # branch-local segments: chunks only pair with themselves; a
        # branch spanning everything pairs every chunk with every chunk
        bounds = chunk_bounds(64, 16)
        assert fold_plan(bounds, 16) == ((0,), (1,), (2,), (3,))
        assert fold_plan(bounds, 64) == ((0, 1, 2, 3),) * 4

    def test_kv_residency_is_pruned_for_local_branches(self, rng):
        # with only segment-local branches, folded chunks' q/k/v blocks
        # must be dropped as the frontier passes them
        q, k, v = _qkv(rng, 64)
        bounds = chunk_bounds(64, 16)
        state = StreamingPrefillState(bounds, [16], [1])
        for i, (a, b) in enumerate(bounds):
            state.ingest(i, q[:, a:b], k[:, a:b], v[:, a:b])
            assert state.resident_blocks() <= 1
        state.finalize()


class TestModelParity:
    def _model(self, **kw):
        return LongNetViT(
            in_chans=48, embed_dim=96, depth=2, slide_ngrids=100,
            segment_length=[16, 32], dilated_ratio="[1, 2]",
            dropout=0.0, drop_path_rate=0.0, **kw,
        )

    def _data(self, rng, N):
        x = jnp.asarray(rng.normal(size=(1, N, 48)), jnp.float32)
        coords = jnp.asarray(
            rng.uniform(0, 100 * 256, (1, N, 2)), jnp.float32
        )
        return x, coords

    def test_streaming_matches_dense_all_layers(self, rng):
        model = self._model()
        x, coords = self._data(rng, 53)
        params = model.init(jax.random.PRNGKey(0), x, coords)["params"]
        dense = model.apply({"params": params}, x, coords,
                            all_layer_embed=True)
        stream = streaming_forward(model, params, x, coords,
                                   chunk_tiles=16, all_layer_embed=True)
        assert len(dense) == len(stream) == 3
        for i, (d, s) in enumerate(zip(dense, stream)):
            np.testing.assert_allclose(
                np.asarray(d, np.float32), np.asarray(s, np.float32),
                atol=1e-5, rtol=0, err_msg=f"layer {i}",
            )

    def test_streaming_matches_dense_global_pool(self, rng):
        x, coords = self._data(rng, 37)
        params = self._model().init(
            jax.random.PRNGKey(0), x, coords
        )["params"]
        model = self._model(global_pool=True)
        dense = model.apply({"params": params}, x, coords)[0]
        stream = streaming_forward(model, params, x, coords,
                                   chunk_tiles=16)[0]
        np.testing.assert_allclose(
            np.asarray(dense, np.float32), np.asarray(stream, np.float32),
            atol=1e-5, rtol=0,
        )

    def test_out_of_order_delivery_is_bit_exact(self, rng):
        """Dist out-of-order arrival: any permutation (plus duplicates)
        executes the identical fold sequence via the frontier buffer."""
        model = self._model()
        x, coords = self._data(rng, 41)
        params = model.init(jax.random.PRNGKey(0), x, coords)["params"]
        xn, cn = np.asarray(x[0]), np.asarray(coords[0])

        def run(order):
            s = StreamingEncoderSession(model, params, 41, chunk_tiles=8)
            for i in order:
                a, b = s.tile_bounds[i]
                s.feed(i, xn[a:b], cn[a:b])
            return np.asarray(s.finalize()[0])

        base = run(range(6))
        perm = run([4, 1, 5, 0, 3, 2, 2, 0])  # permuted + duplicates
        assert np.array_equal(base, perm)

    def test_unsupported_config_refused(self):
        from gigapath_tpu.models.streaming_encoder import check_streamable

        class Cfg:
            multiway = True
            moe_freq = 0
            xpos_rel_pos = False
            deepnorm = False
            encoder_normalize_before = True
            rel_pos_buckets = 0
            max_rel_pos = 0
            layernorm_embedding = False
            vocab_size = -1
            no_output_layer = False

        with pytest.raises(NotImplementedError, match="multiway"):
            check_streamable(Cfg())


class TestMemoryBounded:
    """The acceptance pins: XLA memory analysis + the jaxpr guard."""

    # the 16k smoke geometry (scripts/long_context_smoke.py --stream)
    N16K, CHUNK, H, DH = 16384, 2048, 4, 16

    def _fold_mem(self, total_len):
        from gigapath_tpu.utils.profiling import compiled_memory

        cq = self.CHUNK
        acc_out = jnp.zeros((1, cq, self.H, self.DH), jnp.float32)
        acc_lse = jnp.zeros((1, self.H, cq), jnp.float32)
        q = jnp.zeros((1, cq, self.H, self.DH), jnp.float32)
        fold = functools.partial(fold_pair, segment_len=total_len, ratio=4)
        return compiled_memory(
            fold, acc_out, acc_lse, q, q, q,
            jnp.int32(0), jnp.int32(0), jnp.int32(total_len),
        )

    def test_fold_temp_bytes_flat_in_chunk_count(self):
        """4x the slide length at a fixed chunk size: the per-chunk fold
        executable's arg/temp bytes must not move — per-layer attention
        temporaries are O(chunk) regardless of slide size."""
        mem1 = self._fold_mem(self.N16K)
        mem4 = self._fold_mem(4 * self.N16K)
        assert mem1 and mem1.get("temp_bytes") is not None, mem1
        assert mem4["temp_bytes"] == mem1["temp_bytes"], (mem1, mem4)
        assert mem4["argument_bytes"] == mem1["argument_bytes"], (mem1, mem4)

    def test_fold_beats_dense_at_16k_geometry(self):
        """The adoption threshold: streaming fold temp AND peak < 0.6x
        the dense program's at the 16k smoke geometry (measured ~0.13x;
        0.6 is the acceptance bound, not the expectation)."""
        from gigapath_tpu.utils.profiling import compiled_memory

        n = self.N16K
        q = jnp.zeros((1, n, self.H, self.DH), jnp.float32)
        dense = compiled_memory(
            lambda q, k, v: dilated_attention(
                q, k, v, [1024, 4096, n], [1, 2, 4]
            ),
            q, q, q,
        )
        stream = self._fold_mem(n)
        assert dense and stream, (dense, stream)

        def peak(m):
            return (m["argument_bytes"] + m["temp_bytes"]
                    + m["output_bytes"])

        assert stream["temp_bytes"] < 0.6 * dense["temp_bytes"], (
            stream["temp_bytes"], dense["temp_bytes"],
        )
        assert peak(stream) < 0.6 * peak(dense), (
            peak(stream), peak(dense),
        )

    def test_jaxpr_guard_no_full_length_avals(self):
        """The fold program contains ZERO avals carrying the slide
        length; the dense program (positive control) is full of them —
        so the guard has teeth."""
        L, cq = 1027, 128  # L prime-ish: collides with no block dim
        acc_out = jnp.zeros((1, cq, self.H, self.DH), jnp.float32)
        acc_lse = jnp.zeros((1, self.H, cq), jnp.float32)
        q = jnp.zeros((1, cq, self.H, self.DH), jnp.float32)
        fold = functools.partial(fold_pair, segment_len=L, ratio=2)
        assert full_length_avals(
            fold, acc_out, acc_lse, q, q, q,
            jnp.int32(0), jnp.int32(0), jnp.int32(L), full_len=L,
        ) == []

        qf = jnp.zeros((1, L, self.H, self.DH), jnp.float32)
        dense = lambda q, k, v: dilated_attention(  # noqa: E731
            q, k, v, [64, L], [1, 2]
        )
        assert full_length_avals(dense, qf, qf, qf, full_len=L)


class TestServingSurface:
    def _head(self):
        from gigapath_tpu.models.classification_head import get_model

        return get_model(
            input_dim=24, latent_dim=32, feat_layer="1", n_classes=3,
            model_arch="gigapath_slide_enc_tiny", dtype=None,
        )

    def test_streaming_submitter_matches_head_forward(self, rng):
        from gigapath_tpu.serve.streaming import (
            head_streaming_submitter,
            streaming_head_logits,
        )

        model, params = self._head()
        N = 45
        feats = np.asarray(rng.normal(size=(N, 24)), np.float32)
        coords = np.asarray(rng.uniform(0, 5000, (N, 2)), np.float32)
        dense = np.asarray(model.apply(
            {"params": params}, jnp.asarray(feats[None]),
            jnp.asarray(coords[None]),
        ), np.float32)

        submitter = head_streaming_submitter(model, params, chunk_tiles=16)
        session = submitter.open("s0", N)
        for i, (a, b) in enumerate(session.session.tile_bounds):
            session.feed(i, feats[a:b], coords[a:b])
        logits = streaming_head_logits(model, params, session.result())
        np.testing.assert_allclose(logits, dense, atol=1e-5, rtol=0)
        assert submitter.served == 1

    def test_pipeline_streaming_entry_matches_dense(self, rng):
        from gigapath_tpu.dist.boundary import EmbeddingChunk, plan_chunks
        from gigapath_tpu.pipeline import (
            run_inference_with_slide_encoder,
            run_inference_with_slide_encoder_streaming,
        )

        model = LongNetViT(
            in_chans=32, embed_dim=64, depth=1, slide_ngrids=100,
            segment_length=[16], dilated_ratio="[1]",
            dropout=0.0, drop_path_rate=0.0,
        )
        N = 29
        feats = np.asarray(rng.normal(size=(N, 32)), np.float32)
        coords = np.asarray(rng.uniform(0, 5000, (N, 2)), np.float32)
        params = model.init(
            jax.random.PRNGKey(0), jnp.asarray(feats[None]),
            jnp.asarray(coords[None]),
        )["params"]
        dense = run_inference_with_slide_encoder(
            feats, coords, model, params,
        )
        chunks = [
            EmbeddingChunk.build("s", cid, a, b, feats[a:b],
                                 coords=coords[a:b], digest=False)
            for cid, a, b in plan_chunks(N, 8)
        ]
        stream = run_inference_with_slide_encoder_streaming(
            reversed(chunks), N, model, params, chunk_tiles=8,
        )
        assert dense.keys() == stream.keys()
        for key in dense:
            np.testing.assert_allclose(
                stream[key], dense[key], atol=1e-5, rtol=0,
                err_msg=key,
            )


@pytest.mark.slow
def test_hundred_k_token_stream_smoke():
    """10^5-token ingest through the fold state (reduced width, like the
    smoke scripts — the SEQUENCE scale is what's under test): the
    streaming attention holds up at slide scales the dense path cannot
    assemble on small hosts. Finite outputs, full coverage, and bounded
    chunk residency are the assertions; per-chunk exactness is pinned by
    the default-tier parity tests."""
    L, chunk, H, Dh = 100_000, 4096, 2, 8
    sls, drs = [1024, 32768], [1, 2]
    bounds = chunk_bounds(L, chunk)
    state = StreamingPrefillState(bounds, sls, drs)
    max_resident = 0
    for i, (a, b) in enumerate(bounds):
        block_rng = np.random.default_rng(i)
        q, k, v = (
            jnp.asarray(
                block_rng.standard_normal((1, b - a, H, Dh)), jnp.float32
            )
            for _ in range(3)
        )
        state.ingest(i, q, k, v)
        max_resident = max(max_resident, state.resident_blocks())
    blocks = state.finalize()
    assert sum(blk.shape[1] for blk in blocks) == L
    assert all(np.isfinite(np.asarray(blk)).all() for blk in blocks)
    # residency tracks the widest branch's reach (a 32768 segment spans
    # 8 chunks), never the slide length (25 chunks)
    assert max_resident <= 9, max_resident
