"""Fine-tune harness: optimizer recipe, schedule, checkpointing, end-to-end CLI.

Covers the reference training stack (``finetune/{main,params,training,utils}.py``)
on synthetic fixtures: layer-decay group construction, warmup-cosine values,
gradient accumulation boundary, freeze-as-optimizer-label, Orbax
checkpoint round-trip + best-score monitor + kill-and-resume, and the full
k-fold CLI writing summary.csv (BASELINE config 4's shape, tiny scale).
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pandas as pd
import pytest

from gigapath_tpu.finetune.utils import (
    build_optimizer,
    get_layer_id,
    get_loss_function,
    make_lr_schedule,
    param_labels_lrd,
)
from gigapath_tpu.utils.checkpoint import (
    MonitorScore,
    restore_checkpoint,
    save_checkpoint,
)

D_IN = 16


class TestLayerDecay:
    def test_get_layer_id_mapping(self):
        assert get_layer_id(("slide_encoder", "patch_embed", "proj", "kernel"), 3) == 0
        assert get_layer_id(("slide_encoder", "cls_token"), 3) == 0
        assert get_layer_id(("slide_encoder", "encoder", "layers_1", "ffn"), 3) == 2
        assert get_layer_id(("slide_encoder", "norm", "scale"), 3) == 3
        assert get_layer_id(("classifier", "kernel"), 3) == 3

    def test_labels_and_groups(self):
        params = {
            "slide_encoder": {
                "patch_embed": {"proj": {"kernel": jnp.zeros((4, 4)), "bias": jnp.zeros(4)}},
                "encoder": {"layers_0": {"fc1": {"kernel": jnp.zeros((4, 4))}}},
            },
            "classifier": {"kernel": jnp.zeros((4, 2))},
        }
        labels, groups = param_labels_lrd(params, num_layers=2)
        assert labels["slide_encoder"]["patch_embed"]["proj"]["kernel"] == "layer0_decay"
        assert labels["slide_encoder"]["patch_embed"]["proj"]["bias"] == "layer0_no_decay"
        assert labels["slide_encoder"]["encoder"]["layers_0"]["fc1"]["kernel"] == "layer1_decay"
        assert labels["classifier"]["kernel"] == "layer2_decay"

    def test_deeper_layers_get_larger_updates(self):
        """layer_decay^(num_layers - id): early layers update less."""
        params = {
            "slide_encoder": {
                "patch_embed": {"proj": {"kernel": jnp.ones((4, 4))}},
                "encoder": {"layers_0": {"fc1": {"kernel": jnp.ones((4, 4))}}},
            },
            "classifier": {"kernel": jnp.ones((4, 2))},
        }
        tx = build_optimizer(
            params,
            lr=1.0,
            warmup_epochs=0,
            epochs=1,
            steps_per_epoch=100,
            weight_decay=0.0,
            layer_decay=0.5,
            num_layers=2,
            gc=1,
            lr_scheduler="fixed",
        )
        state = tx.init(params)
        grads = jax.tree.map(jnp.ones_like, params)
        updates, _ = tx.update(grads, state, params)
        u_early = float(
            jnp.abs(updates["slide_encoder"]["patch_embed"]["proj"]["kernel"]).mean()
        )
        u_late = float(jnp.abs(updates["classifier"]["kernel"]).mean())
        # scales: layer0 -> 0.25, layer2 -> 1.0
        assert u_late / u_early == pytest.approx(4.0, rel=0.01)

    def test_freeze_subtree_zeroes_updates(self):
        params = {
            "slide_encoder": {"patch_embed": {"proj": {"kernel": jnp.ones((4, 4))}}},
            "classifier": {"kernel": jnp.ones((4, 2))},
        }
        tx = build_optimizer(
            params,
            lr=1.0,
            warmup_epochs=0,
            epochs=1,
            steps_per_epoch=10,
            layer_decay=1.0,
            num_layers=1,
            gc=1,
            freeze_subtree="slide_encoder",
            lr_scheduler="fixed",
        )
        state = tx.init(params)
        grads = jax.tree.map(jnp.ones_like, params)
        updates, _ = tx.update(grads, state, params)
        assert (
            float(jnp.abs(updates["slide_encoder"]["patch_embed"]["proj"]["kernel"]).sum())
            == 0.0
        )
        assert float(jnp.abs(updates["classifier"]["kernel"]).sum()) > 0

    def test_grad_accumulation_boundary(self):
        params = {"classifier": {"kernel": jnp.ones((2, 2))}}
        tx = build_optimizer(
            params,
            lr=1.0,
            warmup_epochs=0,
            epochs=1,
            steps_per_epoch=10,
            layer_decay=1.0,
            num_layers=1,
            gc=4,
            lr_scheduler="fixed",
        )
        state = tx.init(params)
        grads = jax.tree.map(jnp.ones_like, params)
        for i in range(3):
            updates, state = tx.update(grads, state, params)
            assert float(jnp.abs(updates["classifier"]["kernel"]).sum()) == 0.0
        updates, state = tx.update(grads, state, params)  # 4th: real step
        assert float(jnp.abs(updates["classifier"]["kernel"]).sum()) > 0


class TestSchedule:
    def test_warmup_then_cosine(self):
        sched = make_lr_schedule(
            lr=1.0, min_lr=0.0, warmup_epochs=1, epochs=5, steps_per_epoch=10
        )
        assert float(sched(0)) == 0.0
        assert float(sched(5)) == pytest.approx(0.5)  # mid-warmup
        assert float(sched(10)) == pytest.approx(1.0)  # warmup end
        assert float(sched(50)) == pytest.approx(0.0, abs=1e-6)  # end
        mid = float(sched(30))  # halfway through cosine
        assert mid == pytest.approx(0.5, abs=0.01)

    def test_loss_functions(self, rng):
        ce = get_loss_function({"setting": "multi_class"})
        logits = jnp.asarray(rng.normal(size=(2, 3)), jnp.float32)
        loss = ce(logits, jnp.asarray([0, 2]))
        assert float(loss) > 0
        bce = get_loss_function({"setting": "multi_label"})
        loss2 = bce(logits, jnp.asarray([[1, 0, 1], [0, 1, 0]]))
        assert float(loss2) > 0


class TestCheckpoint:
    def test_roundtrip(self, tmp_path):
        state = {
            "params": {"w": np.arange(6, dtype=np.float32).reshape(2, 3)},
            "epoch": np.asarray(3),
        }
        path = str(tmp_path / "ckpt")
        save_checkpoint(path, state)
        restored = restore_checkpoint(path)
        np.testing.assert_array_equal(restored["params"]["w"], state["params"]["w"])

    def test_monitor_saves_only_improvements(self, tmp_path):
        monitor = MonitorScore()
        path = str(tmp_path / "best")
        assert monitor(0.5, {"v": np.asarray([1.0])}, path)
        assert not monitor(0.4, {"v": np.asarray([2.0])}, path)
        assert monitor(0.6, {"v": np.asarray([3.0])}, path)
        assert restore_checkpoint(path)["v"][0] == 3.0

    def test_kill_and_resume_reproduces_training(self, rng):
        """Save params+opt_state mid-run; resuming reproduces the same
        trajectory as the uninterrupted run (VERDICT r1 next-step 7)."""
        import tempfile

        params = {"w": jnp.asarray(rng.normal(size=(4, 4)), jnp.float32)}
        tx = optax.adamw(1e-2)
        x = jnp.asarray(rng.normal(size=(8, 4)), jnp.float32)

        def loss_fn(p):
            return ((x @ p["w"]) ** 2).mean()

        @jax.jit
        def step(p, s):
            loss, g = jax.value_and_grad(loss_fn)(p)
            u, s = tx.update(g, s, p)
            return optax.apply_updates(p, u), s, loss

        # uninterrupted: 6 steps
        p1, s1 = params, tx.init(params)
        for _ in range(6):
            p1, s1, loss_ref = step(p1, s1)

        # interrupted at 3, checkpoint, resume fresh
        p2, s2 = params, tx.init(params)
        for _ in range(3):
            p2, s2, _ = step(p2, s2)
        with tempfile.TemporaryDirectory() as tmp:
            path = os.path.join(tmp, "ckpt")
            save_checkpoint(path, {"params": jax.device_get(p2), "opt_state": jax.device_get(s2)})
            template = {"params": jax.device_get(p2), "opt_state": jax.device_get(s2)}
            restored = restore_checkpoint(path, template)
        p3, s3 = restored["params"], restored["opt_state"]
        for _ in range(3):
            p3, s3, loss_resumed = step(p3, s3)
        np.testing.assert_allclose(float(loss_ref), float(loss_resumed), rtol=1e-6)
        np.testing.assert_allclose(
            np.asarray(p1["w"]), np.asarray(p3["w"]), atol=1e-6
        )


@pytest.fixture
def finetune_fixture(tmp_path, rng):
    """Synthetic 8-slide h5 dataset + csv + tiny task yaml."""
    import h5py

    root = tmp_path / "h5_files"
    root.mkdir()
    rows = []
    for i in range(8):
        n_tiles = 12 + i
        with h5py.File(root / f"s{i}.h5", "w") as f:
            f.create_dataset(
                "features", data=rng.normal(size=(n_tiles, D_IN)).astype(np.float32)
            )
            f.create_dataset(
                "coords", data=rng.integers(0, 2000, (n_tiles, 2)).astype(np.float32)
            )
        rows.append(
            {"slide_id": f"s{i}.svs", "pat_id": f"p{i}", "label": ["neg", "pos"][i % 2]}
        )
    csv_path = tmp_path / "dataset.csv"
    pd.DataFrame(rows).to_csv(csv_path, index=False)

    yaml_path = tmp_path / "task.yaml"
    yaml_path.write_text(
        "name: toy\nsetting: multi_class\n"
        "label_dict:\n  neg: 0\n  pos: 1\nmax_tiles: 64\nshuffle_tiles: false\n"
    )
    return str(tmp_path), str(csv_path), str(yaml_path), str(root)


def test_finetune_main_end_to_end(finetune_fixture):
    """Two folds of the full CLI on the tiny arch -> summary.csv."""
    from gigapath_tpu.finetune.main import main

    base, csv_path, yaml_path, root = finetune_fixture
    save_dir = os.path.join(base, "out")
    results = main(
        [
            "--task_cfg_path", yaml_path,
            "--dataset_csv", csv_path,
            "--root_path", root,
            "--split_dir", os.path.join(base, "splits"),
            "--save_dir", save_dir,
            "--model_arch", "gigapath_slide_enc_tiny",
            "--input_dim", str(D_IN),
            "--latent_dim", "32",
            "--feat_layer", "1",
            "--folds", "2",
            "--epochs", "2",
            "--warmup_epochs", "1",
            "--gc", "2",
            "--val_r", "0.25",
            "--model_select", "val",
            "--report_to", "jsonl",
            "--dropout", "0.0",
            "--drop_path_rate", "0.0",
        ]
    )
    assert "test_macro_auroc" in results and len(results["test_macro_auroc"]) == 2
    summary = pd.read_csv(
        os.path.join(save_dir, "toy", "eval_toy", "summary.csv")
    )
    assert "val_macro_auroc" in summary.columns
    assert np.isfinite(summary["test_loss"]).all()
