"""Blessed-recipe entry points resolve the reference's exact hyperparameters.

Reference registry: ``scripts/run_panda.sh:6,14-20`` and
``scripts/run_pcam.sh:5-14`` (the shell scripts are the reference's de-facto
hyperparameter store, SURVEY §5.6 #5).
"""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _dry_run(script):
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", script), "--dry"],
        capture_output=True, text=True, check=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    return out.stdout


def test_run_panda_resolves_reference_recipe():
    out = _dry_run("run_panda.py")
    # effective LR = blr * batch_size * gc / 256 = 0.002 * 1 * 32 / 256
    assert "actual lr (blr * bs * gc / 256): 0.00025" in out
    assert "effective batch size: 32" in out
    for line in [
        "max_wsi_size = 250000",
        "epochs = 5",
        "gc = 32",
        "blr = 0.002",
        "optim_wd = 0.05",
        "layer_decay = 0.95",
        "feat_layer = 11",
        "dropout = 0.1",
        "model_select = last_epoch",
        "model_arch = gigapath_slide_enc12l768d",
    ]:
        assert line in out, line


def test_run_panda_cli_override_wins():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "run_panda.py"),
         "--dry", "--epochs", "2"],
        capture_output=True, text=True, check=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    ).stdout
    assert "epochs = 2" in out


def test_run_pcam_resolves_reference_recipe():
    out = _dry_run("run_pcam.py")
    for line in [
        "batch_size = 128",
        "lr = 0.02",
        "min_lr = 0.0",
        "train_iters = 4000",
        "eval_interval = 100",
        "optim = sgd",
        "weight_decay = 0.01",
    ]:
        assert line in out, line
