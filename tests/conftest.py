"""Test configuration: force an 8-device CPU platform before JAX initializes.

The reference has no test suite at all (SURVEY.md §4); here the suite runs on
a virtual 8-device CPU platform (``--xla_force_host_platform_device_count``) so
distributed code paths (mesh sharding, collectives) can be validated without
TPU hardware as they land.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# The axon TPU plugin (sitecustomize in this image) pins jax_platforms before
# user code runs; the env var alone does not stick. Override via config.
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(0)
