"""Test configuration: force an 8-device CPU platform before JAX initializes.

The reference has no test suite at all (SURVEY.md §4); here the suite runs on
a virtual 8-device CPU platform (``--xla_force_host_platform_device_count``) so
distributed code paths (mesh sharding, collectives) can be validated without
TPU hardware as they land.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# The axon TPU plugin (sitecustomize in this image) pins jax_platforms before
# user code runs; the env var alone does not stick. Override via config.
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_addoption(parser):
    parser.addoption(
        "--runslow",
        action="store_true",
        default=False,
        help="also run tests marked slow (interpret-mode Pallas kernels, "
        "mesh suites, multi-minute compile loops)",
    )


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: multi-minute tests (Pallas interpret mode, 8-device mesh "
        "compiles); skipped by default, enabled with --runslow or RUN_SLOW=1",
    )


# Tests >= ~7 s on the 8-device virtual CPU mesh (measured round 5,
# pytest --durations=50 under load; the full suite was ~30 min). Matched
# by nodeid substring so the tier list lives in ONE place; tests may also
# self-mark with @pytest.mark.slow. Everything here has a faster sibling
# covering the same code path in the default tier.
_SLOW_NODEIDS = (
    "test_dilated_attention.py::TestFusedPhaseMajorPath::test_gradients_match_generic",
    "test_dilated_attention.py::TestFusedPhaseMajorPath::test_traced_valid_len_matches_static",
    "test_dilated_attention.py::TestFusedPhaseMajorPath::test_valid_len_and_causal_match_generic",
    "test_dilated_attention.py::TestFusedPhaseMajorPath::test_matches_oracle",
    "test_dilated_attention.py::TestFusedPhaseMajorPath::test_odd_ratio_falls_back",
    "test_dilated_attention.py::test_seq_parallel_matches_single_device",
    "test_dilated_attention.py::test_seq_parallel_causal_matches_single_device",
    "test_dilated_attention.py::TestBHLDFastPath::test_traced_valid_len_gradients",
    "test_dilated_attention.py::TestBHLDFastPath::test_valid_len_matches_generic",
    "test_dilated_attention.py::TestBHLDFastPath::test_jnp_tier_matches_oracle",
    "test_dilated_attention.py::TestBHLDFastPath::test_pallas_tier_matches_oracle",
    "test_dilated_attention.py::TestBHLDFastPath::test_gradients_match_generic",
    "test_dilated_attention.py::TestBHLDFastPath::test_causal_matches_generic",
    "test_dilated_attention.py::TestBHLDFastPath::test_traced_valid_len_matches_generic",
    "test_dilated_attention.py::TestOffsetDecode::test_stepwise_matches_full",
    "test_dilated_attention.py::TestOffsetDecode::test_chunked_matches_full",
    "test_dilated_attention.py::test_fused_streaming_matches_stacked",
    "test_dilated_attention.py::test_streaming_fusion_matches_stacked",
    "test_dilated_attention.py::test_module_gigapath_schedule",
    "test_dilated_attention.py::test_gradients_flow",
    "test_dilated_attention.py::test_multibranch_matches_oracle",
    "test_dilated_attention.py::test_longnet_decoder_incremental_matches_full",
    # round-8 rebalance (durations re-measured, same >= ~7 s bar):
    # seq-parallel ragged routing has test_seq_parallel_fused_routing_fast;
    # the 8-mesh ring-vs-gather A/B has the single-device ragged ring
    # parity + the golden ring-signal ledger pin; the multiclass stream
    # state chain has the epilogue grad-parity + jaxpr siblings
    "test_dilated_attention.py::test_seq_parallel_ragged_mask_fused_routing",
    "test_dilated_attention.py::test_ring_matches_gather_seq_parallel",
    "test_dilated_attention.py::TestStreamFusionEpilogue::test_multiclass_state_chain",
    "test_finetune_harness.py::test_finetune_main_end_to_end",
    "test_moe.py::TestMoEEncoder::test_train_step_moe_aux_weight",
    "test_moe.py::TestMoEEncoder::test_moe_longnet_encoder_trains_one_step",
    "test_moe.py::TestExpertParallel::test_shard_map_all_to_all_matches_serial",
    "test_moe.py::TestExpertParallel::test_gspmd_expert_sharding_matches_single_device",
    "test_moe.py::TestMOELayer::test_output_is_convex_expert_mix",
    "test_encoder.py::test_longnet_remat_matches_plain",
    "test_encoder.py::test_longnet_from_name_small",
    "test_parallel.py::test_sharded_train_step_matches_single_device",
    "test_slide_encoder.py::test_global_pool_differs_from_cls",
    "test_slide_encoder.py::test_forward_shapes",
    "test_decoder_retnet.py::TestEncoderDecoder::test_moe_layers_use_side_specific_dims",
    "test_decoder_retnet.py::TestBertInit::test_trunc_normal_redraw",
    "test_decoder_retnet.py::TestDecoder::test_moe_decoder_layer",
    "test_decoder_retnet.py::TestDecoder::test_incremental_decode_matches_full",
    "test_train_driver.py::test_rename_and_full_journey",
    "test_pad_masking.py::test_slide_encoder_pad_mask_matches_unpadded",
    "test_pad_masking.py::test_slide_encoder_global_pool_excludes_pads",
    "test_pipeline_drivers.py::TestPipeline::test_tile_encode_slide_encode",
    "test_pipeline_drivers.py::TestPretrain::test_mae_loss_decreases",
    "test_pallas_flash.py::test_kv_len_ragged_masking",
    "test_pallas_flash.py::test_gradients_match_reference",
    "test_pallas_flash.py::test_bwd_impl_asymmetric_blocks_match",
    "test_pallas_flash.py::test_kv_len_masks_large_real_keys",
    "test_pallas_flash.py::test_flat_bwd_resegment_fallback_matches",
    "test_beit3.py::TestBEiT3::test_fused_vision_language",
    "test_beit3.py::TestBEiT3::test_single_modality",
    "test_pad_masking.py::test_classification_head_logits_invariant_to_bucket",
    "test_pad_masking.py::test_dilated_attention_valid_len_matches_unpadded",
    "test_slide_encoder.py::test_torch_checkpoint_roundtrip",
    "test_encoder.py::test_remat_with_dropout_traces",
    "test_pipeline_drivers.py::TestPredict::test_predict_writes_csv",
    "test_pallas_flash.py::test_flat_bwd_fallback_masks_invalid_row_cotangents",
)


def pytest_collection_modifyitems(config, items):
    # same truthiness convention as every other repo flag (env_flag in
    # gigapath_tpu/ops/common.py): ''/'0'/'false'/'no' mean OFF
    run_slow = os.environ.get("RUN_SLOW", "").strip().lower() not in (
        "", "0", "false", "no",
    )
    if config.getoption("--runslow") or run_slow:
        return
    skip = pytest.mark.skip(reason="slow tier: pass --runslow (or RUN_SLOW=1)")
    for item in items:
        # exact match on the de-parametrized nodeid: substring matching
        # would also catch tests whose NAME merely extends a listed name
        base = item.nodeid.split("[")[0]
        if "slow" in item.keywords or any(
            base.endswith(nid) for nid in _SLOW_NODEIDS
        ):
            item.add_marker(skip)


@pytest.fixture
def rng():
    return np.random.default_rng(0)


@pytest.fixture(scope="session")
def serve_tiny_model():
    """The ONE tiny f32 serving model shared by test_serve.py and
    test_serve_obs.py (building it costs ~10 s of flax init — paying it
    once per session instead of once per module keeps tier-1 inside its
    wall budget). f32 (dtype=None) because the serving parity bars are
    float32 statements."""
    from gigapath_tpu.models.classification_head import get_model

    return get_model(
        input_dim=16, latent_dim=32, feat_layer="1", n_classes=2,
        model_arch="gigapath_slide_enc_tiny", dtype=None,
    )
