"""Demo: the full WSI inference journey (reference ``demo/run_gigapath.py``):
tile a slide -> encode tiles -> encode the slide.

    python demo/run_gigapath.py <slide> [tile_ckpt] [slide_ckpt]
"""

import glob
import os
import sys

import _bootstrap  # noqa: F401  (repo-checkout sys.path setup)

from gigapath_tpu.pipeline import (
    load_tile_slide_encoder,
    run_inference_with_slide_encoder,
    run_inference_with_tile_encoder,
    tile_one_slide,
)

if __name__ == "__main__":
    slide_path = sys.argv[1] if len(sys.argv) > 1 else "sample_data/slide.png"
    tile_ckpt = sys.argv[2] if len(sys.argv) > 2 else ""
    slide_ckpt = sys.argv[3] if len(sys.argv) > 3 else ""

    save_dir = os.path.join("outputs", "preprocessing")
    print("NOTE: Prov-GigaPath is trained with 0.5 mpp preprocessed slides")
    slide_dir = tile_one_slide(slide_path, save_dir=save_dir, level=0)
    image_paths = sorted(glob.glob(os.path.join(slide_dir, "*.png")))
    print(f"Found {len(image_paths)} image tiles")

    (tile_model, tile_params), (slide_model, slide_params) = load_tile_slide_encoder(
        local_tile_encoder_path=tile_ckpt, local_slide_encoder_path=slide_ckpt
    )
    tile_outputs = run_inference_with_tile_encoder(image_paths, tile_model, tile_params)
    print("tile_embeds:", tile_outputs["tile_embeds"].shape)
    slide_embeds = run_inference_with_slide_encoder(
        tile_outputs["tile_embeds"], tile_outputs["coords"], slide_model, slide_params
    )
    print("last_layer_embed:", slide_embeds["last_layer_embed"].shape)
