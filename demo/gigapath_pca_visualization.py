"""Demo: PCA RGB visualization of tile-encoder patch tokens.

Counterpart of reference ``demo/gigapath_pca_visualization_timm-Copy1.py``:
run the tile encoder in feature mode, project patch tokens to 3 principal
components, render as an RGB overlay per tile.

    python demo/gigapath_pca_visualization.py <tiles_dir> [tile_ckpt] [out.png]
"""

import glob
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np

import _bootstrap  # noqa: F401  (repo-checkout sys.path setup)

from gigapath_tpu.data.transforms import preprocess_tile
from gigapath_tpu.models.tile_encoder import create_tile_encoder

if __name__ == "__main__":
    tiles_dir = sys.argv[1] if len(sys.argv) > 1 else "outputs/preprocessing"
    tile_ckpt = sys.argv[2] if len(sys.argv) > 2 else ""
    out_path = sys.argv[3] if len(sys.argv) > 3 else "outputs/pca_overlay.png"

    model, params = create_tile_encoder(pretrained=tile_ckpt, dtype=jnp.bfloat16)
    paths = sorted(glob.glob(os.path.join(tiles_dir, "**/*.png"), recursive=True))[:16]
    assert paths, f"no tiles under {tiles_dir}"

    from PIL import Image

    imgs = np.stack([preprocess_tile(Image.open(p)) for p in paths])
    tokens = jax.jit(
        lambda p, x: model.apply({"params": p}, x, method=model.forward_features)
    )(params, jnp.asarray(imgs, jnp.bfloat16))
    patch_tokens = np.asarray(tokens[:, 1:], np.float32)  # drop cls

    # PCA to 3 components over all patches
    flat = patch_tokens.reshape(-1, patch_tokens.shape[-1])
    flat = flat - flat.mean(axis=0)
    _, _, vt = np.linalg.svd(flat, full_matrices=False)
    rgb = flat @ vt[:3].T
    rgb = (rgb - rgb.min(0)) / np.ptp(rgb, 0).clip(1e-8)
    grid = model.grid_size
    rgb = rgb.reshape(len(paths), grid, grid, 3)

    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    n = int(np.ceil(np.sqrt(len(paths))))
    fig, axes = plt.subplots(n, 2 * n, figsize=(4 * n, 2 * n))
    for i, p in enumerate(paths):
        r, c = divmod(i, n)
        axes[r][2 * c].imshow(Image.open(p))
        axes[r][2 * c].axis("off")
        axes[r][2 * c + 1].imshow(rgb[i])
        axes[r][2 * c + 1].axis("off")
    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    fig.savefig(out_path)
    print("saved", out_path)
