"""Demo: end-to-end WSI inference + toy biomarker prediction head.

Counterpart of reference ``demo/yuce.py``: the run_gigapath.py journey plus
a randomly-initialized 19-biomarker linear head over the slide embedding
(``yuce.py:64-75``) with wall-clock timing (``yuce.py:15,155-158``).
"""

import glob
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

import _bootstrap  # noqa: F401  (repo-checkout sys.path setup)

from gigapath_tpu.pipeline import (
    load_tile_slide_encoder,
    run_inference_with_slide_encoder,
    run_inference_with_tile_encoder,
    tile_one_slide,
)

BIOMARKERS = [f"biomarker_{i}" for i in range(19)]

if __name__ == "__main__":
    start_time = time.time()
    slide_path = sys.argv[1] if len(sys.argv) > 1 else "sample_data/slide.png"

    slide_dir = tile_one_slide(slide_path, save_dir="outputs/yuce", level=0)
    image_paths = sorted(glob.glob(os.path.join(slide_dir, "*.png")))

    (tile_model, tile_params), (slide_model, slide_params) = load_tile_slide_encoder()
    tile_outputs = run_inference_with_tile_encoder(image_paths, tile_model, tile_params)
    slide_embeds = run_inference_with_slide_encoder(
        tile_outputs["tile_embeds"], tile_outputs["coords"], slide_model, slide_params
    )
    embed = jnp.asarray(slide_embeds["last_layer_embed"])

    # toy randomly-initialized biomarker head, as in the reference demo
    key = jax.random.PRNGKey(0)
    w = jax.random.normal(key, (embed.shape[-1], len(BIOMARKERS))) * 0.02
    probs = np.asarray(jax.nn.sigmoid(embed @ w))[0]
    for name, p in zip(BIOMARKERS, probs):
        print(f"{name}: {p:.3f}")
    print(f"Elapsed: {time.time() - start_time:.2f} s")
