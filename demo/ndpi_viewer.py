"""Demo: interactive WSI pyramid viewer (reference ``demo/ndpi_viewer.py``).

A matplotlib window with sliders for pyramid level and x/y position over any
slide the repo's :class:`SlideReader` can open (OpenSlide formats incl.
.ndpi when the C library is present; plain images via the pyramid
fallback). Pass ``--headless OUT.png`` to render one view to a file
instead of opening a window (CI / no-display environments).

Usage:
    python demo/ndpi_viewer.py slide.ndpi
    python demo/ndpi_viewer.py slide.ndpi --headless outputs/view.png
"""

import argparse
import os

import numpy as np

import _bootstrap  # noqa: F401  (repo-checkout sys.path setup)

from gigapath_tpu.preprocessing.foreground_segmentation import open_slide

VIEW = 1000  # viewport edge in pixels at the selected level


class NDPIViewer:
    """Level/x/y slider viewer over a pyramid reader (reference
    ``NDPIViewer:9-241``, rebuilt on the repo's reader abstraction)."""

    def __init__(self, path: str, headless_out: str | None = None):
        self.reader = open_slide(path)
        self.filename = os.path.basename(path)
        self.level = self.reader.level_count - 1  # start at lowest resolution
        self.x = 0
        self.y = 0

        print(f"file: {self.filename}")
        print(f"dimensions: {self.reader.dimensions}")
        print(f"levels: {self.reader.level_count}")
        for i in range(self.reader.level_count):
            w, h = self.reader.level_dimensions[i]
            print(f"  level {i}: {w} x {h} (downsample {self.reader.level_downsamples[i]})")

        if headless_out:
            self._save(headless_out)
        else:
            self._run_interactive()

    def _view(self) -> np.ndarray:
        w, h = self.reader.level_dimensions[self.level]
        vw, vh = min(VIEW, w), min(VIEW, h)
        x = int(min(self.x, w - vw))
        y = int(min(self.y, h - vh))
        # sliders move in level-local pixels; the reader takes (y, x) in
        # LEVEL-0 coordinates (foreground_segmentation.py:89-92) with the
        # size in level pixels — scale by the level's downsample
        ds = self.reader.level_downsamples[self.level]
        arr = self.reader.read_region(
            (int(y * ds), int(x * ds)), self.level, (vh, vw)
        )
        return np.moveaxis(arr, 0, -1)

    def _save(self, out_path: str):
        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt

        os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
        plt.figure(figsize=(10, 8))
        plt.imshow(self._view())
        plt.title(f"{self.filename} — level {self.level} @ ({self.x}, {self.y})")
        plt.axis("off")
        plt.savefig(out_path, bbox_inches="tight")
        print("saved", out_path)

    def _run_interactive(self):
        import matplotlib.pyplot as plt
        from matplotlib.widgets import Slider

        self.fig, self.ax = plt.subplots(figsize=(10, 8))
        plt.subplots_adjust(bottom=0.25)
        self.image = self.ax.imshow(self._view())
        self.ax.set_title(self.filename)
        self.ax.axis("off")

        ax_level = plt.axes([0.25, 0.15, 0.65, 0.03])
        ax_x = plt.axes([0.25, 0.10, 0.65, 0.03])
        ax_y = plt.axes([0.25, 0.05, 0.65, 0.03])
        w0, h0 = self.reader.level_dimensions[self.level]
        self.s_level = Slider(
            ax_level, "level", 0, self.reader.level_count - 1,
            valinit=self.level, valstep=1,
        )
        self.s_x = Slider(ax_x, "x", 0, max(1, w0 - VIEW), valinit=0, valstep=1)
        self.s_y = Slider(ax_y, "y", 0, max(1, h0 - VIEW), valinit=0, valstep=1)

        def update(_):
            level = int(self.s_level.val)
            if level != self.level:
                self.level = level
                w, h = self.reader.level_dimensions[level]
                # re-range the position sliders for the new level
                self.s_x.valmax = max(1, w - VIEW)
                self.s_y.valmax = max(1, h - VIEW)
                self.s_x.ax.set_xlim(0, self.s_x.valmax)
                self.s_y.ax.set_xlim(0, self.s_y.valmax)
            self.x = int(self.s_x.val)
            self.y = int(self.s_y.val)
            self.image.set_data(self._view())
            self.fig.canvas.draw_idle()

        self.s_level.on_changed(update)
        self.s_x.on_changed(update)
        self.s_y.on_changed(update)
        plt.show()


if __name__ == "__main__":
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("slide", help="path to a WSI (.ndpi/.svs/.tiff) or image")
    ap.add_argument("--headless", metavar="OUT", default=None,
                    help="render one view to OUT instead of opening a window")
    args = ap.parse_args()
    NDPIViewer(args.slide, headless_out=args.headless)
