"""Demo: render a slide thumbnail to a PNG (reference ``demo/show_slide.py``,
sans interactive window — headless image save)."""

import sys

import numpy as np

import _bootstrap  # noqa: F401  (repo-checkout sys.path setup)

from gigapath_tpu.preprocessing.foreground_segmentation import open_slide

if __name__ == "__main__":
    slide_path = sys.argv[1] if len(sys.argv) > 1 else "sample_data/slide.png"
    out_path = sys.argv[2] if len(sys.argv) > 2 else "outputs/slide_view.png"

    reader = open_slide(slide_path)
    print("levels:", reader.level_count)
    print("dimensions per level:", reader.level_dimensions)
    arr = reader.read_level(reader.level_count - 1)

    import os

    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    plt.figure(figsize=(8, 8))
    plt.imshow(np.moveaxis(arr, 0, -1))
    plt.axis("off")
    plt.savefig(out_path, bbox_inches="tight")
    print("saved", out_path)
    reader.close()
