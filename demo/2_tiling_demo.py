"""Demo: tile one slide (counterpart of reference ``demo/2_tiling_demo.py``)."""

import os
import sys

import _bootstrap  # noqa: F401  (repo-checkout sys.path setup)

from gigapath_tpu.pipeline import tile_one_slide

if __name__ == "__main__":
    slide_path = sys.argv[1] if len(sys.argv) > 1 else "sample_data/slide.png"
    save_dir = sys.argv[2] if len(sys.argv) > 2 else os.path.join("outputs", "preprocessing")
    # The reference tiles at level 1 for its 0.5 MPP slide; plain images have
    # a single level
    tile_one_slide(slide_path, save_dir=save_dir, level=0)
    print("NOTE: tiling dependency libs can be tricky; the tiles are saved under", save_dir)
