"""Demo: slide encoder forward on synthetic tile embeddings.

Counterpart of reference ``demo/4_load_slide_encoder.py`` (BASELINE
config 3): N=512 synthetic 1536-d embeddings + coords through
gigapath_slide_enc12l768d.
"""

import sys

import jax
import jax.numpy as jnp
import numpy as np

import _bootstrap  # noqa: F401  (repo-checkout sys.path setup)

from gigapath_tpu.models import slide_encoder

if __name__ == "__main__":
    ckpt = sys.argv[1] if len(sys.argv) > 1 else ""
    model, params = slide_encoder.create_model(
        ckpt, "gigapath_slide_enc12l768d", 1536, dtype=jnp.bfloat16
    )
    n_params = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))
    print("param #", n_params)

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(1, 512, 1536)), jnp.bfloat16)
    coords = jnp.asarray(rng.uniform(0, 250000, (1, 512, 2)), jnp.float32)
    out = jax.jit(lambda p, x, c: model.apply({"params": p}, x, c))(params, x, coords)
    print("slide embedding:", out[0].shape, out[0].dtype)
