"""Demo: export a region of a slide at every pyramid level
(reference ``demo/ndpi_extractor.py``: per-level region export)."""

import os
import sys

import numpy as np
from PIL import Image

import _bootstrap  # noqa: F401  (repo-checkout sys.path setup)

from gigapath_tpu.preprocessing.foreground_segmentation import open_slide

if __name__ == "__main__":
    slide_path = sys.argv[1] if len(sys.argv) > 1 else "sample_data/slide.png"
    out_dir = sys.argv[2] if len(sys.argv) > 2 else "outputs/regions"
    y, x = (int(a) for a in sys.argv[3:5]) if len(sys.argv) > 4 else (0, 0)
    size = int(sys.argv[5]) if len(sys.argv) > 5 else 256

    os.makedirs(out_dir, exist_ok=True)
    reader = open_slide(slide_path)
    for level in range(reader.level_count):
        region = reader.read_region((y, x), level, (size, size))
        out = os.path.join(out_dir, f"level_{level}.png")
        Image.fromarray(np.moveaxis(region, 0, -1)).save(out)
        print(f"level {level} (downsample {reader.level_downsamples[level]}): {out}")
    reader.close()
