"""Demo: toy slide classification — mean-pooled tile embeddings + sklearn
logistic regression (reference ``demo/fenlei.py``: encode tiles, mean-pool,
LogisticRegression over a handful of slides).

    python demo/fenlei.py <slides_dir_with_pngs> [tile_ckpt]
"""

import glob
import os
import sys

import numpy as np

import _bootstrap  # noqa: F401  (repo-checkout sys.path setup)

from gigapath_tpu.pipeline import (
    load_tile_slide_encoder,
    run_inference_with_tile_encoder,
    tile_one_slide,
)

if __name__ == "__main__":
    slides_dir = sys.argv[1] if len(sys.argv) > 1 else "sample_data"
    tile_ckpt = sys.argv[2] if len(sys.argv) > 2 else ""

    slide_files = sorted(
        glob.glob(os.path.join(slides_dir, "*.png"))
        + glob.glob(os.path.join(slides_dir, "*.svs"))
    )
    assert len(slide_files) >= 2, "need at least two slides for the toy classifier"

    (tile_model, tile_params), _ = load_tile_slide_encoder(
        local_tile_encoder_path=tile_ckpt
    )

    feats, labels = [], []
    for i, slide in enumerate(slide_files):
        slide_dir = tile_one_slide(slide, save_dir="outputs/fenlei", level=0)
        tiles = sorted(glob.glob(os.path.join(slide_dir, "*.png")))
        out = run_inference_with_tile_encoder(tiles, tile_model, tile_params)
        feats.append(out["tile_embeds"].mean(axis=0))
        labels.append(i % 2)  # toy labels, as in the reference demo

    from sklearn.linear_model import LogisticRegression

    clf = LogisticRegression(max_iter=1000).fit(np.stack(feats), labels)
    print("train accuracy:", clf.score(np.stack(feats), labels))
