"""Demo: load the ViT-G/14 tile encoder and check golden-output parity.

Counterpart of reference ``demo/3_load_tile_encoder.py:28-34`` — the repo's
only numerical-parity anchor: the tile embedding of
``images/prov_normal_000_1.png`` must match the stored golden ``.pt`` within
atol 1e-2. Requires local checkpoint + golden files (zero-egress build):

    python demo/3_load_tile_encoder.py <tile_encoder.pth> <img.png> <golden.pt>
"""

import sys

import jax
import jax.numpy as jnp
import numpy as np

import _bootstrap  # noqa: F401  (repo-checkout sys.path setup)

from gigapath_tpu.data.transforms import preprocess_tile
from gigapath_tpu.models.tile_encoder import count_params, create_tile_encoder

if __name__ == "__main__":
    ckpt = sys.argv[1] if len(sys.argv) > 1 else ""
    img_path = sys.argv[2] if len(sys.argv) > 2 else "images/prov_normal_000_1.png"
    golden_path = sys.argv[3] if len(sys.argv) > 3 else "images/prov_normal_000_1.pt"

    model, params = create_tile_encoder(pretrained=ckpt)
    print("param #", count_params(model))

    from PIL import Image

    sample_input = preprocess_tile(Image.open(img_path))[None]
    output = jax.jit(lambda p, x: model.apply({"params": p}, x))(
        params, jnp.asarray(sample_input)
    )[0]
    print("Model output:", output.shape)
    print(np.asarray(output))

    import os

    if os.path.exists(golden_path):
        import torch

        expected = torch.load(golden_path, map_location="cpu").numpy()
        print("Expected output:", expected.shape)
        assert np.allclose(np.asarray(output, np.float32), expected, atol=1e-2), (
            "golden-output parity FAILED"
        )
        print("Golden-output parity PASSED (atol 1e-2)")
    else:
        print(f"(golden file {golden_path} not present; skipping parity assert)")
