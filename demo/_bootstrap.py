"""Put the repo root on sys.path so `python demo/<script>.py` works from a
checkout without installation (python puts demo/ itself on sys.path, which
is how this module is found)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
