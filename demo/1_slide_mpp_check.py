"""Demo: resolve the pyramid level matching the 0.5 MPP training resolution.

Counterpart of reference ``demo/1_slide_mpp_check.py`` (minus the HF-hub
sample download — pass a local slide path; zero-egress build).
"""

import sys

import _bootstrap  # noqa: F401  (repo-checkout sys.path setup)

from gigapath_tpu.data.slide_utils import find_level_for_target_mpp

if __name__ == "__main__":
    slide_path = sys.argv[1] if len(sys.argv) > 1 else "sample_data/slide.ndpi"
    print("NOTE: Prov-GigaPath is trained with 0.5 mpp preprocessed slides")
    target_mpp = 0.5
    level = find_level_for_target_mpp(slide_path, target_mpp)
    if level is not None:
        print(f"Found level: {level}")
    else:
        print("No suitable level found.")
