"""Headline benchmark: PANDA-scale slide embedding throughput on one chip.

Runs the flagship slide encoder (gigapath_slide_enc12l768d, 86M params,
5-branch dilated attention) forward over N=10240 tile embeddings — the
"PANDA slide-embed wallclock" north star from BASELINE.md — in bf16 under
jit, and reports tokens/sec.

Timing: iterations are chained inside one jitted fori_loop with a forced
data dependency and two loop counts are differenced, because the axon tunnel
makes per-call host timing meaningless (see gigapath_tpu/utils/timing.py).

vs_baseline: the reference publishes no numbers (BASELINE.md), so the
denominator is an analytic estimate of the reference stack on its stated
hardware (1x A100, fp16 autocast, flash-attn) running the *same workload*,
with the FLOP count computed exactly from the flagship config below
(12 layers x [qkv/out + FFN GEMMs] + the 5-branch dilated-attention
schedule + patch embed ~= 3.0 TFLOP per 10240-token slide). Per branch,
head group p attends only its own dilation phase's tokens, so each of the
H heads runs m = ceil(g/r) queries x m keys per segment: branch cost =
4*E*L*m/r FLOPs, NOT 4*E*L*m (each token is queried by H/r heads, not H).
A100 fp16 at a generous 35% end-to-end MFU => ~109 TFLOPS =>
~27.6 ms/slide => ~3.7e5 tokens/s. Generous because the reference's
dilated gather/scatter/recombination runs in eager torch between
flash-attn calls.

Prints exactly one JSON line.
"""

import json

import jax.numpy as jnp
import numpy as np

N = 10240

# flagship gigapath_slide_enc12l768d geometry, from the single source of
# truth (reference slide_encoder.py:137-154)
from gigapath_tpu.models.longnet_config import flagship_geometry

_G = flagship_geometry()
DEPTH, E, FFN, IN_CHANS = _G["depth"], _G["embed_dim"], _G["ffn_dim"], _G["in_chans"]
SEGS, RATIOS = _G["segment_lengths"], _G["dilated_ratios"]
A100_FP16_FLOPS = 312e12
A100_MFU = 0.35


def workload_flops(n_tokens: int) -> float:
    """Analytic forward FLOPs of one slide at n_tokens (+cls) tokens."""
    L = n_tokens + 1  # cls token
    gemms = DEPTH * (4 * 2 * L * E * E + 2 * 2 * L * E * FFN)
    # per branch: every head attends m x m per segment on 1/r of the tokens
    # => 4 * E * L * m / r (see module docstring)
    windows = sum(
        -(-min(sl, L) // r) / r for sl, r in zip(SEGS, RATIOS)
    )
    attn = DEPTH * 4 * L * E * windows
    patch = 2 * L * IN_CHANS * E
    return float(gemms + attn + patch)


A100_REF_TOKENS_PER_SEC = N / (workload_flops(N) / (A100_FP16_FLOPS * A100_MFU))


def main():
    import jax

    from gigapath_tpu.models import slide_encoder
    from gigapath_tpu.utils.timing import chained_seconds_per_iter

    model, params = slide_encoder.create_model(
        "", "gigapath_slide_enc12l768d", in_chans=1536, dtype=jnp.bfloat16
    )
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(1, N, 1536)), jnp.bfloat16)
    coords = jnp.asarray(rng.uniform(0, 250000, (1, N, 2)), jnp.float32)

    def step(x, params, coords):
        out = model.apply({"params": params}, x, coords)[0]  # [1, 768]
        # feed a (numerically negligible) function of the output back into
        # the input so the loop body cannot be hoisted out of fori_loop
        return x + (out.sum() * 1e-30).astype(x.dtype)

    sec_per_iter, overhead = chained_seconds_per_iter(step, x, args=(params, coords))
    tokens_per_sec = N / sec_per_iter

    # train-step variant (fwd+bwd, the reference's actual hot loop —
    # finetune/training.py:223-282): grad of a scalar readout wrt params
    def train_step(x, params, coords):
        def loss_fn(p):
            return model.apply({"params": p}, x, coords)[0].astype(jnp.float32).var()

        grads = jax.grad(loss_fn)(params)
        # depend on EVERY grad leaf — depending on one would let XLA DCE all
        # other weight-gradient matmuls and overstate the throughput
        total = sum(g.sum().astype(jnp.float32) for g in jax.tree.leaves(grads))
        return x + (total * 1e-30).astype(x.dtype)

    sec_train, _ = chained_seconds_per_iter(
        train_step, x, args=(params, coords), iters_low=2, iters_high=8
    )
    train_tokens_per_sec = N / sec_train

    print(
        json.dumps(
            {
                "metric": "slide_embed_tokens_per_sec",
                "value": round(tokens_per_sec, 1),
                "unit": "tokens/s",
                "vs_baseline": round(tokens_per_sec / A100_REF_TOKENS_PER_SEC, 3),
                "train_tokens_per_sec": round(train_tokens_per_sec, 1),
            }
        )
    )


if __name__ == "__main__":
    main()
