"""Headline benchmark: PANDA-scale slide embedding + ViT-G tile encoding.

Two workloads, one JSON line:

1. **Slide encoder** (gigapath_slide_enc12l768d, 86M params, 5-branch
   dilated attention) forward + train step over N=10240 tile embeddings —
   the "PANDA slide-embed wallclock" north star from BASELINE.md — in bf16
   under jit, reported as tokens/sec.
2. **Tile encoder** (ViT-G/14, 1.13B params) batch-128 bf16 jitted forward
   — the literal tiles/sec/chip north-star metric, mirroring the
   reference's inference recipe (``gigapath/pipeline.py:141-161``: batches
   of 128 tiles under fp16 autocast).

Timing: iterations are chained inside one jitted fori_loop with a forced
data dependency and two loop counts are differenced, because the axon tunnel
makes per-call host timing meaningless (see gigapath_tpu/utils/timing.py).

vs_baseline: the reference publishes no numbers (BASELINE.md), so the
denominator is an analytic estimate of the reference stack on its stated
hardware (1x A100, fp16 autocast, flash-attn) running the *same workload*,
with the FLOP count computed exactly from the flagship config below
(12 layers x [qkv/out + FFN GEMMs] + the 5-branch dilated-attention
schedule + patch embed ~= 3.0 TFLOP per 10240-token slide). Per branch,
head group p attends only its own dilation phase's tokens, so each of the
H heads runs m = ceil(g/r) queries x m keys per segment: branch cost =
4*E*L*m/r FLOPs, NOT 4*E*L*m (each token is queried by H/r heads, not H).
A100 fp16 at a generous 35% end-to-end MFU => ~109 TFLOPS =>
~27.6 ms/slide => ~3.7e5 tokens/s. Generous because the reference's
dilated gather/scatter/recombination runs in eager torch between
flash-attn calls. The baseline value + version ride in the JSON line so
rounds computed under different denominators stay comparable
(``baseline_version`` history: v1 = per-branch cost 4*E*L*m, v2 = the
corrected 4*E*L*m/r used since round 2).

``mfu`` / ``tile_mfu`` ground the numbers in hardware terms: measured
FLOP/s over the chip's peak bf16 FLOP/s. Denominator bases differ by
design: ``mfu`` always uses the analytic slide workload count (the same
count the baseline is computed from, so the two stay comparable);
``tile_mfu`` prefers compiled-HLO cost analysis and falls back to the
analytic ViT count.

Prints exactly one JSON line on stdout. An obs telemetry stream
(run_start/step/run_end events, gigapath_tpu.obs schema) rides stderr and
appends to BENCH_OBS.jsonl — every BENCH_LOCAL.json snapshot write lands
there as a run_end event, so stale-number provenance is queryable.
"""

import json
import os
import sys
import time
from typing import Tuple

import jax.numpy as jnp
import numpy as np

# Written on every successful run; read back as the stale-number fallback
# when backend acquisition fails at round end (the BENCH_r03/r04 rc=1
# failure mode: two rounds of engineering invisible to the driver because
# one flaky tunnel RPC zeroed the record).
LOCAL_SNAPSHOT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "BENCH_LOCAL.json")

# Append-only telemetry stream (gigapath_tpu.obs schema): every bench run
# emits run_start/step/run_end events here — including a run_end carrying
# each BENCH_LOCAL.json snapshot write, so stale-number provenance is
# queryable long after the one-line stdout contract scrolled away.
OBS_STREAM = os.path.join(os.path.dirname(os.path.abspath(__file__)), "BENCH_OBS.jsonl")

# Per-run perf ledger (gigapath_tpu.obs.ledger): the compiled artifact's
# cost/memory analysis + jaxpr fingerprints for the bench workloads,
# diffable across commits with scripts/ledger_diff.py. The path rides the
# JSON line ("ledger") so every published number carries a pointer to its
# compiled-artifact profile.
BENCH_LEDGER = os.path.join(os.path.dirname(os.path.abspath(__file__)), "BENCH_LEDGER.json")

N = 10240
TILE_BATCH = 128  # reference pipeline.py:141

# flagship gigapath_slide_enc12l768d geometry, from the single source of
# truth (reference slide_encoder.py:137-154)
from gigapath_tpu.models.longnet_config import flagship_geometry

_G = flagship_geometry()
DEPTH, E, FFN, IN_CHANS = _G["depth"], _G["embed_dim"], _G["ffn_dim"], _G["in_chans"]
SEGS, RATIOS = _G["segment_lengths"], _G["dilated_ratios"]
A100_FP16_FLOPS = 312e12
A100_MFU = 0.35
BASELINE_VERSION = "analytic-a100-v2-perbranch"

# peak dense bf16 FLOP/s by TPU generation (public spec sheets); override
# with TPU_PEAK_FLOPS for unlisted hardware
_PEAK_BY_KIND = {
    "v4": 275e12,
    "v5 lite": 197e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v6": 918e12,
}


def _probe_backend_subprocess(timeout_s: float) -> Tuple[bool, str]:
    """Bounded out-of-process backend probe.

    The tunnel has two failure modes: a fast 'Unable to initialize backend
    axon: UNAVAILABLE' (BENCH_r04) and an indefinite HANG inside the first
    jax.devices() (observed round 5) — the latter cannot be timed out
    in-process (the init RPC blocks in C++ with no deadline), so each
    attempt probes in a subprocess that a hard timeout can kill."""
    import subprocess

    code = "import jax; d = jax.devices(); print(d[0].device_kind)"
    try:
        res = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, text=True, timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        return False, f"probe hung >{timeout_s:.0f}s (killed)"
    if res.returncode == 0:
        return True, res.stdout.strip().splitlines()[-1] if res.stdout else ""
    tail = (res.stderr or "").strip().splitlines()
    return False, tail[-1] if tail else f"probe rc={res.returncode}"


_BACKEND_READY = False


def acquire_backend(attempts: int = 4, delays=(10, 30, 60), probe_timeout=150.0):
    """First jax.devices() with bounded, hang-proof retries.

    Each attempt first probes backend init in a subprocess under a hard
    timeout (see _probe_backend_subprocess); only after a probe succeeds
    does the in-process init run — at that point it is overwhelmingly
    likely to complete quickly. Raises after all attempts so main() can
    emit the contractual JSON line with the stale-snapshot fallback.
    Success is memoized: once the in-process backend is up, later calls
    (e.g. chip_peak_flops) must not spawn further subprocess probes — a
    second probe is one extra roll of the flaky-tunnel dice, and on
    exclusive-lock runtimes it would fail against our own process.
    """
    global _BACKEND_READY
    if _BACKEND_READY:
        import jax

        return jax.devices()
    last = "unknown"
    for i in range(attempts):
        ok, msg = _probe_backend_subprocess(probe_timeout)
        if ok:
            import jax

            devices = jax.devices()
            _BACKEND_READY = True
            return devices
        last = msg
        print(
            f"bench: backend probe {i + 1}/{attempts} failed: {msg}",
            file=sys.stderr,
        )
        if i < attempts - 1:
            time.sleep(delays[min(i, len(delays) - 1)])
    raise RuntimeError(f"backend unavailable after {attempts} probes: {last}")


def chip_peak_flops() -> float:
    env = os.environ.get("TPU_PEAK_FLOPS")
    if env:
        return float(env)
    kind = acquire_backend()[0].device_kind.lower()
    for key, val in _PEAK_BY_KIND.items():
        if key in kind:
            return val
    return 197e12  # default to v5e


def workload_flops(n_tokens: int) -> float:
    """Analytic forward FLOPs of one slide at n_tokens (+cls) tokens."""
    L = n_tokens + 1  # cls token
    gemms = DEPTH * (4 * 2 * L * E * E + 2 * 2 * L * E * FFN)
    # per branch: every head attends m x m per segment on 1/r of the tokens
    # => 4 * E * L * m / r (see module docstring)
    windows = sum(
        -(-min(sl, L) // r) / r for sl, r in zip(SEGS, RATIOS)
    )
    attn = DEPTH * 4 * L * E * windows
    patch = 2 * L * IN_CHANS * E
    return float(gemms + attn + patch)


A100_REF_TOKENS_PER_SEC = N / (workload_flops(N) / (A100_FP16_FLOPS * A100_MFU))


def tile_workload_flops(model) -> float:
    """Analytic forward FLOPs of ONE tile through the ViT-G/14 encoder.

    SwiGLU MLP: packed fc1 is [d -> hidden] where hidden already counts
    both gate+value mats, and fc2 is [hidden/2 -> d]: per token
    2*d*hidden + 2*d*hidden/2 = 3*d*hidden FLOPs. Used both as the
    compiled-HLO fallback for tile_mfu and as the workload count behind
    the analytic A100 tile baseline (same treatment the slide encoder's
    baseline got): BASELINE.md's north star is tiles/sec vs 1xA100
    running the reference recipe (``gigapath/pipeline.py:141-161``)."""
    L = model.num_patches + 1
    hidden = model.mlp_hidden_dim
    d = model.embed_dim
    p = model.patch_size
    per_layer = 4 * 2 * L * d * d + 3 * L * d * hidden + 4 * L * L * d
    return float(model.depth * per_layer + 2 * L * 3 * p * p * d)


def bench_tile_encoder(peak_flops: float, ledger=None):
    """Batch-128 bf16 ViT-G/14 forward: (tiles/sec, mfu)."""
    import jax

    from gigapath_tpu.models.tile_encoder import gigapath_tile_enc
    from gigapath_tpu.obs.ledger import NullLedger
    from gigapath_tpu.utils.timing import chained_seconds_per_iter

    ledger = ledger if ledger is not None else NullLedger()

    model = gigapath_tile_enc(dtype=jnp.bfloat16)
    rng = jax.random.PRNGKey(0)
    x0 = jnp.zeros((1, 224, 224, 3), jnp.float32)
    # init on-device under jit: a host-side 4.5 GB fp32 init + transfer is
    # both slow and needless for a throughput measurement
    params = jax.jit(lambda r: model.init(r, x0)["params"])(rng)
    imgs = jnp.asarray(
        np.random.default_rng(0).normal(size=(TILE_BATCH, 224, 224, 3)),
        jnp.bfloat16,
    )

    def step(x, params):
        out = model.apply({"params": params}, x)  # [B, 1536]
        return x + (out.sum() * 1e-30).astype(x.dtype)

    sec_per_iter, _ = chained_seconds_per_iter(
        step, imgs, args=(params,), iters_low=2, iters_high=8
    )
    tiles_per_sec = TILE_BATCH / sec_per_iter

    # params as an ARG: closed-over params become 4.5 GB of inline constants
    # in the lowered HLO (and overflow the remote-compile request)
    entry = ledger.capture_full(
        "tile_forward", lambda x, p: model.apply({"params": p}, x), imgs, params
    )
    flops = ((entry or {}).get("cost") or {}).get("flops")
    mfu_source = "compiled_hlo"
    if not flops or not np.isfinite(flops):
        print(
            "bench: tile_mfu falling back to analytic FLOP count "
            f"(compiled_flops returned {flops!r})",
            file=sys.stderr,
        )
        flops = TILE_BATCH * tile_workload_flops(model)
        mfu_source = "analytic"
    mfu = (flops / sec_per_iter) / peak_flops
    # analytic A100 denominator for the tiles/sec north star, mirroring
    # the slide encoder's baseline treatment (same MFU assumption)
    baseline_tiles_per_sec = (A100_FP16_FLOPS * A100_MFU) / tile_workload_flops(model)
    return tiles_per_sec, mfu, baseline_tiles_per_sec, mfu_source


def run_bench(runlog=None, ledger=None) -> dict:
    import jax

    from gigapath_tpu.models import slide_encoder
    from gigapath_tpu.obs import NullRunLog, span
    from gigapath_tpu.obs.ledger import NullLedger
    from gigapath_tpu.utils.timing import chained_seconds_per_iter

    runlog = runlog if runlog is not None else NullRunLog(driver="bench")
    ledger = ledger if ledger is not None else NullLedger()

    # retried init FIRST, unconditionally: with TPU_PEAK_FLOPS set,
    # chip_peak_flops alone would never touch jax and the first (un-retried)
    # backend init would happen inside model creation — the BENCH_r04 mode
    devices = acquire_backend()
    peak = chip_peak_flops()
    runlog.event(
        "heartbeat", phase="backend_up", device_kind=devices[0].device_kind,
        device_count=len(devices), peak_flops=peak,
    )

    model, params = slide_encoder.create_model(
        "", "gigapath_slide_enc12l768d", in_chans=1536, dtype=jnp.bfloat16
    )
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(1, N, 1536)), jnp.bfloat16)
    coords = jnp.asarray(rng.uniform(0, 250000, (1, N, 2)), jnp.float32)

    def step(x, params, coords):
        out = model.apply({"params": params}, x, coords)[0]  # [1, 768]
        # feed a (numerically negligible) function of the output back into
        # the input so the loop body cannot be hoisted out of fori_loop
        return x + (out.sum() * 1e-30).astype(x.dtype)

    with span("slide_forward", runlog):
        sec_per_iter, overhead = chained_seconds_per_iter(step, x, args=(params, coords))
    tokens_per_sec = N / sec_per_iter
    mfu = (workload_flops(N) / sec_per_iter) / peak
    runlog.step(0, wall_s=sec_per_iter, synced=True, workload="slide_forward",
                tokens_per_sec=tokens_per_sec, mfu=mfu)

    # compiled-artifact profile of the headline workload: cost analysis
    # (FLOPs) + memory analysis (peak HBM) + jaxpr fingerprint, ledgered
    # under "slide_forward" and surfaced as headline JSON fields
    entry = ledger.capture_full(
        "slide_forward", lambda x, p: model.apply({"params": p}, x, coords)[0],
        x, params,
    )
    mem = (entry or {}).get("memory")
    # the ledger already sanitizes non-finite analysis values to None, so
    # nothing here can leak a NaN into the contractual JSON line
    slide_flops = ((entry or {}).get("cost") or {}).get("flops")
    peak_hbm_gb = None
    if mem and mem.get("temp_bytes") is not None and mem.get("argument_bytes") is not None:
        peak_hbm_gb = round((mem["temp_bytes"] + mem["argument_bytes"]) / 2**30, 2)

    # train-step variant (fwd+bwd, the reference's actual hot loop —
    # finetune/training.py:223-282): grad of a scalar readout wrt params
    def train_step(x, params, coords):
        def loss_fn(p):
            return model.apply({"params": p}, x, coords)[0].astype(jnp.float32).var()

        grads = jax.grad(loss_fn)(params)
        # depend on EVERY grad leaf — depending on one would let XLA DCE all
        # other weight-gradient matmuls and overstate the throughput
        total = sum(g.sum().astype(jnp.float32) for g in jax.tree.leaves(grads))
        return x + (total * 1e-30).astype(x.dtype)

    with span("slide_train", runlog):
        sec_train, _ = chained_seconds_per_iter(
            train_step, x, args=(params, coords), iters_low=2, iters_high=8
        )
    train_tokens_per_sec = N / sec_train
    runlog.step(1, wall_s=sec_train, synced=True, workload="slide_train",
                tokens_per_sec=train_tokens_per_sec)

    try:
        with span("tile_forward", runlog):
            tile_tiles_per_sec, tile_mfu, tile_baseline, tile_mfu_source = (
                bench_tile_encoder(peak, ledger=ledger)
            )
        tile_vs_baseline = round(tile_tiles_per_sec / tile_baseline, 3)
        runlog.step(2, wall_s=TILE_BATCH / tile_tiles_per_sec, synced=True,
                    workload="tile_forward", tiles_per_sec=tile_tiles_per_sec,
                    mfu=tile_mfu)
        tile_tiles_per_sec = round(tile_tiles_per_sec, 1)
        tile_mfu = round(tile_mfu, 3)
        tile_baseline = round(tile_baseline, 1)
    except Exception as e:  # the headline metric must survive a tile failure
        # stderr: stdout is contractually exactly one JSON line
        print(f"tile-encoder bench failed: {type(e).__name__}: {e}", file=sys.stderr)
        runlog.error("bench.tile_encoder", e)
        tile_tiles_per_sec, tile_mfu, tile_baseline, tile_vs_baseline = (
            None, None, None, None,
        )
        tile_mfu_source = None

    return {
        "metric": "slide_embed_tokens_per_sec",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": round(tokens_per_sec / A100_REF_TOKENS_PER_SEC, 3),
        "train_tokens_per_sec": round(train_tokens_per_sec, 1),
        "mfu": round(mfu, 3),
        "peak_hbm_gb": peak_hbm_gb,
        "compiled_flops": slide_flops,
        "ledger": ledger.path,
        "tile_tiles_per_sec": tile_tiles_per_sec,
        "tile_mfu": tile_mfu,
        "tile_mfu_source": tile_mfu_source,
        "tile_vs_baseline": tile_vs_baseline,
        "tile_baseline_tiles_per_sec": tile_baseline,
        "baseline_tokens_per_sec": round(A100_REF_TOKENS_PER_SEC, 1),
        "baseline_version": BASELINE_VERSION,
    }


def main():
    """Print exactly one JSON line; exit 0 even on failure.

    On success the payload is also snapshotted to BENCH_LOCAL.json. On
    failure (after acquire_backend's bounded retries) the JSON line still
    honors the contract — but ``"value"`` stays ``null``: an unmeasured
    round must never be recordable as a fresh number (the round-5 advisor
    finding: consumers that don't check ``"stale"`` would republish the
    old snapshot as this round's result). The last successful snapshot is
    reported only under ``"last_good"`` / ``"last_good_value"``, with
    ``"stale": true`` and the ``"error"``, so the record degrades to
    "here is the last measured number, clearly labeled" — never to
    "unmeasured number that looks fresh".
    """
    from gigapath_tpu.obs import get_run_log
    from gigapath_tpu.obs.ledger import PerfLedger

    # telemetry stream rides stderr + BENCH_OBS.jsonl: stdout stays the
    # one contractual JSON line. probe_devices=False — backend init is
    # acquire_backend's hang-proofed job, never the manifest's.
    runlog = get_run_log(
        "bench", path=OBS_STREAM, echo_stream=sys.stderr, probe_devices=False,
        config={"n_tokens": N, "tile_batch": TILE_BATCH,
                "baseline_version": BASELINE_VERSION},
    )
    # the ledger always CAPTURES (compiled_flops/peak_hbm_gb are bench
    # measurements, not telemetry); GIGAPATH_OBS=0 only suppresses the
    # artifact file + events ("ledger" stays null in the JSON line).
    # autowrite=False: the file lands only on SUCCESS, so a failed run
    # cannot overwrite the last good run's ledger with a partial one
    # (the failure JSON deliberately carries no "ledger" pointer).
    recording = getattr(runlog, "path", None) is not None
    ledger = PerfLedger(runlog, path=BENCH_LEDGER if recording else None,
                        autowrite=False)
    try:
        payload = run_bench(runlog, ledger=ledger)
    except Exception as e:  # noqa: BLE001 — contract: always print the JSON line
        import traceback

        traceback.print_exc(file=sys.stderr)
        runlog.error("bench.run_bench", e)
        payload = {
            "metric": "slide_embed_tokens_per_sec",
            "value": None,
            "unit": "tokens/s",
            "error": f"{type(e).__name__}: {e}",
        }
        if os.path.exists(LOCAL_SNAPSHOT):
            try:
                with open(LOCAL_SNAPSHOT) as f:
                    snap = json.load(f)
                # only trust snapshots this script wrote on SUCCESS: a
                # success snapshot always has a measured numeric value
                snap.pop("error", None)
                snap.pop("stale", None)
                payload["stale"] = True
                payload["last_good"] = snap
                payload["last_good_value"] = snap.get("value")
                payload["last_good_snapshot_utc"] = snap.get("snapshot_utc")
            except Exception as snap_err:
                print(f"bench: snapshot unreadable: {snap_err}", file=sys.stderr)
        runlog.run_end(
            status="error", error=payload["error"],
            stale=payload.get("stale", False),
            last_good_value=payload.get("last_good_value"),
            last_good_snapshot_utc=payload.get("last_good_snapshot_utc"),
        )
        print(json.dumps(payload))
        return
    payload["snapshot_utc"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    try:
        ledger.write()  # success: publish the run's compiled-artifact ledger
    except Exception as ledger_err:
        print(f"bench: ledger write failed: {ledger_err}", file=sys.stderr)
        # without the file, the pointer would name the PREVIOUS run's
        # ledger — stale provenance masquerading as this run's profile
        if payload.get("ledger") is not None:
            payload["ledger"] = None
    snapshot_written = True
    try:
        with open(LOCAL_SNAPSHOT, "w") as f:
            json.dump(payload, f, indent=1)
            f.write("\n")
    except Exception as snap_err:
        snapshot_written = False
        print(f"bench: snapshot write failed: {snap_err}", file=sys.stderr)
    # the snapshot write IS an event: stale-number provenance stays
    # queryable from the obs stream even after later runs overwrite it
    runlog.run_end(
        status="ok", snapshot_path=LOCAL_SNAPSHOT,
        snapshot_written=snapshot_written, **payload,
    )
    print(json.dumps(payload))


if __name__ == "__main__":
    main()
